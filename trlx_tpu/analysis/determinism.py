"""Determinism-discipline pass (GL90x): no nondeterminism source is
reachable from the **bit-equivalence-critical root set**.

The repo's second load-bearing invariant (after SPMD collective
discipline) is that every new collection path stays bit-identical to the
serial reference: the pipelined, continuous-batching, and async collectors
are all tested as ``store == serial store``, preempt/resume is tested as
``trajectory == uninterrupted trajectory``, and the spool protocol's
requeue-on-actor-death only works because chunk ``i`` regenerates
identically. One wall-clock read feeding saved state, one unsorted
directory scan, one ``set`` iteration feeding ordered output — and a
divergence appears that no unit test pins to a line.

**The root set** (:data:`BIT_EQUIVALENCE_ROOTS`, resolved by
``callgraph.resolve_root_names`` and closed over the same edges jit
tracing uses): the serial reference collection paths
(``make_experience`` / ``_collect_serial`` and their finalize stages),
store serialization (``export_history``, ``collate``), the spool
protocol (``FileExperienceQueue`` + payload flattening), checkpoint
save/restore (``save_state`` / ``restore_state`` / ``maybe_resume`` /
the trainer's ``save``/``load`` and the elastic restore), and
``FaultPlan`` parsing (a plan parsed differently on two ranks fires
different faults).

**The codes** — all scoped to root-reachable functions:

- GL901 — a wall-clock source (``time.time``, ``time.time_ns``,
  ``datetime.now/utcnow/today``) feeding store or checkpoint content.
  Telemetry and span timestamps are exempt via the metric/span
  registries' home modules (:data:`TIMESTAMP_EXEMPT_PATHS` — the
  observability package and the tracker stream own wall-clock
  semantics; their output is diagnostics, never restored state).
- GL902 — module-level ``random.*`` or unseeded ``np.random.*`` global
  RNG use (instance constructors — ``random.Random(seed)``,
  ``np.random.RandomState``/``default_rng`` — are the fix and are
  exempt).
- GL903 — an ``os.listdir`` / ``glob.glob`` / ``Path.iterdir``-family
  scan consumed without ``sorted()`` at the call site: directory order
  is filesystem-dependent, so a spool or checkpoint scan ordered by it
  diverges across hosts and reruns.
- GL904 — iteration over a local ``set`` (literal, ``set()`` call,
  comprehension, or set algebra) feeding ordered output: Python set
  order is salted per process, so any ordered consumer diverges run to
  run. ``sorted(s)`` is the fix and is exempt.
"""

import ast
from typing import Dict, List, Optional, Set

from trlx_tpu.analysis.callgraph import CallGraph, FunctionInfo, attr_chain
from trlx_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    LintPass,
    register_pass,
)

__all__ = ["DeterminismPass", "BIT_EQUIVALENCE_ROOTS", "TIMESTAMP_EXEMPT_PATHS"]

# The bit-equivalence-critical root set (docs/STATIC_ANALYSIS.md "The
# bit-equivalence-critical root set"). Dotted patterns match the qualname
# suffix; bare names match every function/method with that name — the
# abstract `make_experience` deliberately pulls every trainer's collection
# path in, exactly like the jit-root closure does for `loss_fn`.
BIT_EQUIVALENCE_ROOTS = (
    # serial reference collection paths + their finalize stages (running
    # reward moments are order-sensitive; every other collector is tested
    # bit-identical against these)
    "make_experience",
    "make_experience_seq2seq",
    "_collect_serial",
    # store serialization (replay-buffer export + train-batch collation)
    "export_history",
    "collate",
    # spool-protocol ordering: chunk commit/consume and payload round-trip
    "FileExperienceQueue.put",
    "FileExperienceQueue.get",
    "FileExperienceQueue.committed_indices",
    "FileExperienceQueue.cursor",
    "flatten_payload",
    "unflatten_payload",
    # checkpoint save/restore (+ the elastic reshard restore)
    "save_state",
    "restore_state",
    "restore_state_elastic",
    "build_manifest",
    "read_extra",
    "newest_committed_checkpoint",
    "prune_checkpoints",
    "_checkpoint_step_dirs",
    "TPUBaseTrainer.save",
    "TPUBaseTrainer.load",
    "TPUBaseTrainer.maybe_resume",
    # fault-plan parsing: two ranks parsing one plan differently fire
    # different faults — divergence by construction
    "FaultPlan.parse",
    "FaultPlan.from_config",
    # serve KV re-land paths: a host-tier re-land writes spilled bytes
    # back VERBATIM (bit-equality by construction, docs/SERVING.md), and
    # a preemption re-lands the committed prompt prefix through the radix
    # chain — any nondeterminism here silently breaks the "re-landed
    # prefix == cold prefill" pin the serve tests rely on
    "HostTier.reland_many",
    "ContinuousEngine._reland_from_tier",
    "ContinuousEngine._preempt_slot",
    "ContinuousEngine._preempt_for_priority",
)

# Modules whose wall-clock reads are telemetry, not content: the
# observability package (spans/metrics/flight recorder) and the tracker
# stream publish diagnostics that are never restored or replayed.
TIMESTAMP_EXEMPT_PATHS = (
    "trlx_tpu/observability/",
    "trlx_tpu/utils/trackers.py",
)

_WALL_CLOCK = frozenset({
    "time.time",
    "time.time_ns",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
})

# global-RNG constructors that ARE the fix (seeded instances)
_SEEDED_RNG = frozenset({
    "random.Random",
    "random.SystemRandom",
    "numpy.random.RandomState",
    "numpy.random.default_rng",
    "numpy.random.Generator",
})

_DIR_SCANS = frozenset({"os.listdir", "os.scandir", "glob.glob", "glob.iglob"})
_DIR_SCAN_METHODS = frozenset({"iterdir", "glob", "rglob"})


def _in_sorted(mod, node: ast.AST) -> bool:
    """Is ``node`` (a scan/iteration source) inside a ``sorted(...)`` call
    within its own statement? The call-site wrap is the rule: a scan whose
    order is laundered through intermediate state is exactly the bug."""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.stmt):
            return False
        if (
            isinstance(anc, ast.Call)
            and isinstance(anc.func, ast.Name)
            and anc.func.id in ("sorted", "len", "set", "frozenset", "min", "max", "sum")
        ):
            # sorted() restores determinism; len/min/max/sum and a set
            # destination are order-free consumers
            return True
    return False


@register_pass
class DeterminismPass(LintPass):
    name = "determinism"
    codes = ("GL901", "GL902", "GL903", "GL904")
    description = "nondeterminism reachable from bit-equivalence-critical roots"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        graph = ctx.callgraph
        roots = graph.resolve_root_names(BIT_EQUIVALENCE_ROOTS)
        if not roots:
            return []
        reach = graph.reach_from(roots)
        findings: List[Finding] = []
        for fn in graph.functions:
            via = reach.get(fn.full)
            if via is None:
                continue
            if any(fn.module.relpath.startswith(p) for p in TIMESTAMP_EXEMPT_PATHS):
                exempt_clock = True
            else:
                exempt_clock = False
            findings.extend(self._check_fn(graph, fn, via, exempt_clock))
        findings.sort(key=lambda f: (f.path, f.line, f.code))
        return findings

    def _check_fn(
        self, graph: CallGraph, fn: FunctionInfo, via: str, exempt_clock: bool
    ) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[str] = set()

        def emit(code: str, line: int, detail: str, message: str) -> None:
            if detail in seen:
                return
            seen.add(detail)
            findings.append(
                Finding(
                    code=code,
                    path=fn.module.relpath,
                    line=line,
                    symbol=fn.qualname,
                    detail=detail,
                    message=f"{message} — reachable from bit-equivalence-"
                    f"critical root `{via}` (docs/STATIC_ANALYSIS.md)",
                )
            )

        set_locals = self._set_locals(fn)
        for node in fn.body_nodes():
            if isinstance(node, ast.Call):
                name = graph.external_name(node.func, fn, fn.module)
                if name in _WALL_CLOCK and not exempt_clock:
                    emit(
                        "GL901", node.lineno, name,
                        f"wall-clock read `{name}()` feeds content on a "
                        "bit-equivalence-critical path: two runs (or two "
                        "ranks) produce different bytes — derive the value "
                        "from step/epoch counters, or move it to the "
                        "telemetry stream",
                    )
                elif name and (
                    name.startswith("random.") or name.startswith("numpy.random.")
                ) and name not in _SEEDED_RNG:
                    # NOT gated on the timestamp exemption: telemetry modules
                    # own wall-clock semantics, but global RNG on a
                    # bit-critical path is a divergence wherever it lives
                    emit(
                        "GL902", node.lineno, name,
                        f"global-RNG call `{name}()` on a bit-equivalence-"
                        "critical path: module-level RNG state is shared and "
                        "order-dependent — thread an explicit seeded "
                        "generator (random.Random(seed) / "
                        "np.random.default_rng(seed)) instead",
                    )
                elif name in _DIR_SCANS and not _in_sorted(fn.module, node):
                    emit(
                        "GL903", node.lineno, name,
                        f"`{name}()` order is filesystem-dependent; consumed "
                        "without `sorted()` a spool/checkpoint scan diverges "
                        "across hosts and reruns — wrap the scan in "
                        "`sorted(...)` at the call site",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _DIR_SCAN_METHODS
                    and name is None
                    and not _in_sorted(fn.module, node)
                ):
                    # Path-object scans: p.iterdir()/p.glob(...) on a local
                    emit(
                        "GL903", node.lineno, f".{node.func.attr}",
                        f"`.{node.func.attr}()` order is filesystem-"
                        "dependent; wrap the scan in `sorted(...)` at the "
                        "call site",
                    )
            # GL904: ordered iteration over a set-typed local
            iters: List[ast.AST] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp)):
                iters.extend(gen.iter for gen in node.generators)
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                if node.func.id in ("list", "tuple") and node.args:
                    iters.append(node.args[0])
            for it in iters:
                if (
                    isinstance(it, ast.Name)
                    and it.id in set_locals
                    and not _in_sorted(fn.module, it)
                ):
                    emit(
                        "GL904", it.lineno, it.id,
                        f"iteration over set-typed local `{it.id}` feeds "
                        "ordered output: set order is salted per process — "
                        f"iterate `sorted({it.id})`",
                    )
                elif isinstance(it, (ast.Set, ast.SetComp)) and not _in_sorted(
                    fn.module, it
                ):
                    emit(
                        "GL904", it.lineno, "<set-literal>",
                        "iteration over a set expression feeds ordered "
                        "output: set order is salted per process — wrap it "
                        "in `sorted(...)`",
                    )
        return findings

    def _set_locals(self, fn: FunctionInfo) -> Set[str]:
        """Locals assigned from a set-producing expression in ``fn``."""

        def is_set_expr(expr: ast.AST, known: Set[str]) -> bool:
            if isinstance(expr, (ast.Set, ast.SetComp)):
                return True
            if (
                isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Name)
                and expr.func.id in ("set", "frozenset")
            ):
                return True
            if isinstance(expr, ast.Name):
                return expr.id in known
            if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
            ):
                return is_set_expr(expr.left, known) or is_set_expr(
                    expr.right, known
                )
            return False

        out: Set[str] = set()
        nonset: Set[str] = set()
        # two sweeps so `b = a | other` resolves through `a = set(...)`
        for _ in range(2):
            for node in fn.body_nodes():
                if not isinstance(node, ast.Assign):
                    continue
                hit = is_set_expr(node.value, out)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        (out if hit else nonset).add(t.id)
        # a name ALSO assigned from a non-set expression is out: the common
        # `seen = sorted(seen)` rebind launders the set into a list, and
        # path-insensitive tracking must not flag iterating the result
        return out - nonset
