"""Unified generation Engine (docs/PERFORMANCE.md):

- :mod:`trlx_tpu.engine.core` — the Engine interface, the serial
  reference wrapper, and the continuous-batching engine over dense or
  paged KV backends;
- :mod:`trlx_tpu.engine.allocator` — refcounted KV-block allocator;
- :mod:`trlx_tpu.engine.prefix_cache` — radix prefix cache over prompt
  token chunks mapping to committed KV blocks.

The device half (block pool layout, gather/scatter, slot-refill
programs) lives in ``trlx_tpu/ops/paged_kv.py`` and
``trlx_tpu/ops/slot_refill.py``.
"""

from trlx_tpu.engine.allocator import BlockAllocator, BlockPoolExhausted
from trlx_tpu.engine.core import (
    CompletedSequence,
    ContinuousEngine,
    Engine,
    EngineStats,
    SerialEngine,
)
from trlx_tpu.engine.prefix_cache import PrefixCache

__all__ = [
    "BlockAllocator",
    "BlockPoolExhausted",
    "CompletedSequence",
    "ContinuousEngine",
    "Engine",
    "EngineStats",
    "PrefixCache",
    "SerialEngine",
]
