"""Host-side prefix cache: a radix tree over prompt token prefixes mapping
to committed KV blocks.

Eval prompts and GRPO group members share long prompt prefixes and, before
this cache, re-prefilled them on every collection. Here each FULL prompt
block (``block_size`` cache columns entirely inside the prompt region) is
keyed by its *(token, mask) chunk chained on its parent block* — the radix
property: two padded prompt rows that agree on columns ``[0, t)`` (tokens
AND attention mask) have bit-identical KV for those columns at every
layer, because column ``j``'s KV depends only on columns ``≤ j``. A lookup
walks the chain from the root and returns the longest committed run of
full blocks; the engine points the new row's block table at them
(refcount++ via the allocator — copy-on-write: shared blocks are full and
immutable, writes only ever target fresh private blocks) and prefills only
the unshared suffix.

Alignment caveat (docs/PERFORMANCE.md): keys cover the *padded* row from
column 0, so sharing requires identical left padding — exactly what
repeated eval prompts and GRPO groups (identical full prompts) have.
Cross-length text prefixes under different pad widths do not align and
miss; a right-padded or offset-keyed scheme would recover them at the cost
of positional invariance, which left-padded decode does not have.

Entries hold their own allocator ref, so cached blocks survive the
sequences that produced them; eviction (LRU, leaves first — an interior
entry is unreachable without its parent) drops that ref, and the block is
actually freed once no live row shares it. The engine evicts on pool
pressure and on the optional ``capacity_blocks`` cap.

Single-threaded by design, like the engine that owns it (see the thread-
affinity note in ``trlx_tpu/engine/core.py``).

Entry refs are object-scoped ownership: ``insert`` retains blocks into the
cache's own entry table, ``evict``/``clear`` drop them — declared to
graftlint's ownership pass with the ``(object)`` handle spec (GL80x,
docs/STATIC_ANALYSIS.md), which documents the protocol without per-caller
handle tracking.

Multi-tenant isolation (docs/SERVING.md): every chain is rooted at a
per-tenant root uid, so two tenants submitting byte-identical prompts
build DISJOINT radix chains — tenant B can never match (hence never read)
tenant A's committed blocks. The trainer's own traffic is the ``None``
tenant, sharing one default root, byte-for-byte the pre-tenancy behavior.

Host-RAM tiering hook (``trlx_tpu/serve/tiering.py``): when ``spill`` is
set, evicted entries are offered to it BEFORE their allocator ref drops —
the engine's callback copies the block's pool rows to a bounded host pool,
keyed by the entry's content-chained digest (tenant tag + chunk bytes
hashed along the chain, stable across evict/re-insert cycles, unlike
uids). A later identical prompt re-lands those bytes device-side instead
of re-prefilling them.
"""

import hashlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.engine.allocator import BlockAllocator

__all__ = ["PrefixCache"]


@dataclass
class _Entry:
    key: Tuple[int, bytes]  # (parent uid, chunk bytes)
    uid: int
    block: int  # physical pool block holding this chunk's KV
    children: int = 0
    last_used: int = 0
    parent: Optional["_Entry"] = None
    tenant: Optional[str] = None
    digest: bytes = b""  # content-chained id (set when a spill hook exists)


_ROOT_UID = -1


class PrefixCache:
    def __init__(self, block_size: int, capacity_blocks: int = 0):
        self.block_size = int(block_size)
        self.capacity_blocks = int(capacity_blocks)
        self._entries: Dict[Tuple[int, bytes], _Entry] = {}
        self._next_uid = 0
        self._clock = 0
        # per-tenant radix roots: chains chain on parent uid, so distinct
        # roots make tenant trees disjoint by construction
        self._tenant_roots: Dict[Optional[str], int] = {None: _ROOT_UID}
        self._next_root = _ROOT_UID - 1
        # host-tiering spill hook: called with each evicted entry before
        # its allocator ref is dropped (never on clear — clear means the
        # params changed and the KV bytes are invalid everywhere)
        self.spill: Optional[Callable[[_Entry], None]] = None

    def _root_uid(self, tenant: Optional[str]) -> int:
        uid = self._tenant_roots.get(tenant)
        if uid is None:
            uid = self._next_root
            self._next_root -= 1
            self._tenant_roots[tenant] = uid
        return uid

    def _root_digest(self, tenant: Optional[str]) -> bytes:
        return hashlib.sha1(repr(tenant).encode()).digest()

    def chain_digests(
        self,
        tokens: np.ndarray,
        mask: np.ndarray,
        n: int,
        tenant: Optional[str] = None,
    ) -> List[bytes]:
        """Content-chained digests of the first ``n`` full prompt blocks —
        digest ``i`` identifies the padded prompt's columns ``[0, (i+1) *
        block_size)`` under this tenant, independent of entry uids (which
        do not survive evict/re-insert). The host tier is keyed by these."""
        out: List[bytes] = []
        d = self._root_digest(tenant)
        for i in range(min(n, self._full_blocks(tokens.shape[0]))):
            d = hashlib.sha1(d + self._chunk_key(tokens, mask, i)).digest()
            out.append(d)
        return out

    def __len__(self) -> int:
        return len(self._entries)

    def _chunk_key(self, tokens: np.ndarray, mask: np.ndarray, i: int) -> bytes:
        a, b = i * self.block_size, (i + 1) * self.block_size
        return (
            np.ascontiguousarray(tokens[a:b], np.int32).tobytes()
            + np.ascontiguousarray(mask[a:b] > 0, np.int8).tobytes()
        )

    def _full_blocks(self, prompt_len: int) -> int:
        """Blocks entirely inside the prompt region ``[0, prompt_len)`` —
        the only immutable (hence cacheable) ones: the block straddling the
        prompt/response boundary is written during decode."""
        return prompt_len // self.block_size

    def match(
        self,
        tokens: np.ndarray,
        mask: np.ndarray,
        tenant: Optional[str] = None,
    ) -> List[int]:
        """Longest committed chain of full prompt blocks for this padded
        row under ``tenant``'s root; returns their physical block ids (the
        caller retains them)."""
        n_full = self._full_blocks(tokens.shape[0])
        blocks: List[int] = []
        parent_uid = self._root_uid(tenant)
        for i in range(n_full):
            entry = self._entries.get((parent_uid, self._chunk_key(tokens, mask, i)))
            if entry is None:
                break
            self._clock += 1
            entry.last_used = self._clock
            blocks.append(entry.block)
            parent_uid = entry.uid
        return blocks

    def insert(  # acquires: prefix-entry-ref(object)
        self,
        tokens: np.ndarray,
        mask: np.ndarray,
        blocks: List[int],  # the row's table prefix: one id per full block
        allocator: BlockAllocator,
        tenant: Optional[str] = None,
    ) -> int:
        """Commit a freshly prefilled row's full prompt blocks under
        ``tenant``'s root. Chunks already present are left alone (a
        concurrent duplicate keeps its private copy until harvest frees
        it); new entries retain their block so it outlives the row.
        Returns entries inserted."""
        n = min(self._full_blocks(tokens.shape[0]), len(blocks))
        inserted = 0
        parent: Optional[_Entry] = None
        parent_uid = self._root_uid(tenant)
        digests: List[bytes] = (
            self.chain_digests(tokens, mask, n, tenant)
            if self.spill is not None
            else []
        )
        for i in range(n):
            key = (parent_uid, self._chunk_key(tokens, mask, i))
            entry = self._entries.get(key)
            if entry is None:
                self._clock += 1
                entry = _Entry(
                    key=key,
                    uid=self._next_uid,
                    block=blocks[i],
                    last_used=self._clock,
                    parent=parent,
                    tenant=tenant,
                    digest=digests[i] if digests else b"",
                )
                self._next_uid += 1
                allocator.retain([entry.block])
                self._entries[key] = entry
                if parent is not None:
                    parent.children += 1
                inserted += 1
            parent = entry
            parent_uid = entry.uid
        if self.capacity_blocks > 0 and len(self._entries) > self.capacity_blocks:
            self.evict(
                allocator, entries=len(self._entries) - self.capacity_blocks
            )
        return inserted

    def evict(  # releases: prefix-entry-ref(object)
        self,
        allocator: BlockAllocator,
        blocks_needed: int = 0,
        entries: int = 0,
        tenant: Optional[str] = ...,
    ) -> int:
        """Drop LRU leaf entries until ``blocks_needed`` blocks came FREE
        (refs shared with live rows free later, at the rows' release) or
        ``entries`` entries are gone, whichever target was given; returns
        blocks actually freed. ``tenant`` (when given, including ``None``
        for the default namespace) restricts victims to that tenant's
        entries — the quota-pressure eviction path, which must never shed
        another tenant's working set. Each victim is offered to the
        ``spill`` hook (host tiering) before its ref drops: committed
        block KV is immutable, so the copy is valid even while a live row
        still shares the block."""
        freed = 0
        dropped = 0
        while self._entries:
            if blocks_needed > 0 and freed >= blocks_needed:
                break
            if entries > 0 and dropped >= entries:
                break
            if blocks_needed <= 0 and entries <= 0:
                break
            leaves = [
                e
                for e in self._entries.values()
                if e.children == 0 and (tenant is ... or e.tenant == tenant)
            ]
            if not leaves:
                break
            victim = min(leaves, key=lambda e: e.last_used)
            del self._entries[victim.key]
            if victim.parent is not None:
                victim.parent.children -= 1
            if self.spill is not None and victim.digest:
                self.spill(victim)
            freed += len(allocator.release([victim.block]))
            dropped += 1
        return freed

    def clear(self, allocator: BlockAllocator) -> None:  # releases: prefix-entry-ref(object)
        """Release every entry's ref (end-of-engine teardown)."""
        for entry in self._entries.values():
            allocator.release([entry.block])
        self._entries.clear()
