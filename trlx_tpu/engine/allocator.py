"""Refcounted KV-block allocator (host half of the paged cache).

Owns the free list over the pool built by ``ops/paged_kv.py``. Every block
carries a reference count: a decoding slot holds one ref on each block its
table points at, and the prefix cache (``prefix_cache.py``) holds one ref
on each block it has committed — copy-on-write sharing is just "several
holders, refcount > 1, nobody writes" (writes only ever target
freshly-allocated refcount-1 blocks; shared blocks are full and immutable).

Block 0 is the reserved all-zeros block (``paged_kv.ZERO_BLOCK``): never
allocated, never freed — fresh table entries point there so gathers of
unallocated regions reproduce the dense cache's zeros.

Single-threaded by design, like the engine that owns it (see the thread-
affinity note in ``trlx_tpu/engine/core.py``).

The acquire/release protocol is declared to graftlint's ownership pass
(``# acquires:`` / ``# releases:`` on the methods below; GL80x,
docs/STATIC_ANALYSIS.md): a caller holding a ``kv-block-ref`` must release
it on every exit — including exception paths — or transfer ownership
(store it on the engine's per-slot state, commit it to the prefix cache).
"""

from collections import deque
from typing import Deque, Dict, Iterable, List

from trlx_tpu.ops.paged_kv import ZERO_BLOCK

__all__ = ["BlockPoolExhausted", "BlockAllocator"]


class BlockPoolExhausted(RuntimeError):
    """Raised when an allocation cannot be satisfied even after the caller
    evicted everything evictable — ``engine.max_kv_blocks`` is too small
    for the live working set."""


class BlockAllocator:
    """Free-list + refcount bookkeeping over ``max_blocks`` pool rows."""

    def __init__(self, max_blocks: int):
        if max_blocks < 2:
            raise ValueError(
                f"max_blocks {max_blocks} leaves no allocatable block beyond "
                "the reserved zero block"
            )
        self.max_blocks = int(max_blocks)
        # FIFO reuse keeps recycling deterministic (and spreads writes over
        # the pool, which makes stale-data masking bugs surface in tests
        # rather than hide behind just-zeroed blocks)
        self._free: Deque[int] = deque(
            b for b in range(self.max_blocks) if b != ZERO_BLOCK
        )
        self._refcount: Dict[int, int] = {}
        self.high_water = 0  # max blocks simultaneously in use

    # -- queries ---------------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        return len(self._refcount)

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._refcount.get(block, 0)

    # -- transitions -----------------------------------------------------

    def alloc(self, n: int) -> List[int]:  # acquires: kv-block-ref
        """Take ``n`` fresh blocks (refcount 1 each). Raises
        :class:`BlockPoolExhausted` when the free list is short — the
        engine catches this once, evicts prefix-cache entries, and retries
        before giving up."""
        if n > len(self._free):
            raise BlockPoolExhausted(
                f"need {n} KV blocks, {len(self._free)} free "
                f"({self.blocks_in_use}/{self.max_blocks - 1} in use) — "
                "raise engine.max_kv_blocks or shrink the slot batch"
            )
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._refcount[b] = 1
        self.high_water = max(self.high_water, self.blocks_in_use)
        return out

    def retain(self, blocks: Iterable[int]) -> None:  # acquires: kv-block-ref(arg)
        """One more holder for already-allocated blocks (prefix-cache hit)."""
        for b in blocks:
            if b not in self._refcount:
                raise ValueError(f"retain of unallocated block {b}")
            self._refcount[b] += 1

    def release(self, blocks: Iterable[int]) -> List[int]:  # releases: kv-block-ref(arg)
        """Drop one ref per block; returns the blocks that became free."""
        freed: List[int] = []
        for b in blocks:
            count = self._refcount.get(b)
            if count is None:
                raise ValueError(f"release of unallocated block {b}")
            if count == 1:
                del self._refcount[b]
                self._free.append(b)
                freed.append(b)
            else:
                self._refcount[b] = count - 1
        return freed
