"""Refcounted KV-block allocator (host half of the paged cache).

Owns the free list over the pool built by ``ops/paged_kv.py``. Every block
carries a reference count: a decoding slot holds one ref on each block its
table points at, and the prefix cache (``prefix_cache.py``) holds one ref
on each block it has committed — copy-on-write sharing is just "several
holders, refcount > 1, nobody writes" (writes only ever target
freshly-allocated refcount-1 blocks; shared blocks are full and immutable).

Block 0 is the reserved all-zeros block (``paged_kv.ZERO_BLOCK``): never
allocated, never freed — fresh table entries point there so gathers of
unallocated regions reproduce the dense cache's zeros.

Single-threaded by design, like the engine that owns it (see the thread-
affinity note in ``trlx_tpu/engine/core.py``).

The acquire/release protocol is declared to graftlint's ownership pass
(``# acquires:`` / ``# releases:`` on the methods below; GL80x,
docs/STATIC_ANALYSIS.md): a caller holding a ``kv-block-ref`` must release
it on every exit — including exception paths — or transfer ownership
(store it on the engine's per-slot state, commit it to the prefix cache).
"""

from collections import deque
from typing import Deque, Dict, Iterable, List, Optional

from trlx_tpu.ops.paged_kv import ZERO_BLOCK

__all__ = ["BlockPoolExhausted", "TenantQuotaExceeded", "BlockAllocator"]


class BlockPoolExhausted(RuntimeError):
    """Raised when an allocation cannot be satisfied even after the caller
    evicted everything evictable — ``engine.max_kv_blocks`` is too small
    for the live working set."""


class TenantQuotaExceeded(RuntimeError):
    """Raised when an allocation would push a quota'd tenant past its
    per-tenant block budget (``serve.tenant_quota_blocks``). Deliberately
    NOT a :class:`BlockPoolExhausted`: the pool may have plenty of free
    blocks — the remedy is evicting THIS tenant's prefix entries (or
    failing the request), never global eviction."""


class BlockAllocator:
    """Free-list + refcount bookkeeping over ``max_blocks`` pool rows."""

    def __init__(self, max_blocks: int):
        if max_blocks < 2:
            raise ValueError(
                f"max_blocks {max_blocks} leaves no allocatable block beyond "
                "the reserved zero block"
            )
        self.max_blocks = int(max_blocks)
        # FIFO reuse keeps recycling deterministic (and spreads writes over
        # the pool, which makes stale-data masking bugs surface in tests
        # rather than hide behind just-zeroed blocks)
        self._free: Deque[int] = deque(
            b for b in range(self.max_blocks) if b != ZERO_BLOCK
        )
        self._refcount: Dict[int, int] = {}
        self.high_water = 0  # max blocks simultaneously in use
        # multi-tenant accounting (serve frontend, docs/SERVING.md): a
        # block allocated on behalf of a named tenant counts against that
        # tenant's budget until it is actually FREED (ownership is fixed
        # for the block's lifetime — cross-tenant sharing never happens,
        # the prefix cache namespaces per tenant). ``tenant=None`` is the
        # trainer's unquoted default: unowned, uncounted, unchanged.
        self._quota: Dict[str, int] = {}
        self._owner: Dict[int, str] = {}
        self._tenant_used: Dict[str, int] = {}

    # -- queries ---------------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        return len(self._refcount)

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._refcount.get(block, 0)

    def tenant_blocks_in_use(self, tenant: str) -> int:
        return self._tenant_used.get(tenant, 0)

    def tenant_quota(self, tenant: str) -> Optional[int]:
        return self._quota.get(tenant)

    # -- tenancy ---------------------------------------------------------

    def set_tenant_quota(self, tenant: str, blocks: int) -> None:
        """Cap ``tenant``'s simultaneously-owned blocks. Applies to future
        allocations only; an already-over tenant simply cannot allocate
        until its usage drains below the new cap."""
        if blocks < 1:
            raise ValueError(
                f"tenant quota for {tenant!r} must be >= 1, got {blocks}"
            )
        self._quota[tenant] = int(blocks)

    # -- transitions -----------------------------------------------------

    def alloc(self, n: int, tenant: Optional[str] = None) -> List[int]:  # acquires: kv-block-ref
        """Take ``n`` fresh blocks (refcount 1 each). Raises
        :class:`BlockPoolExhausted` when the free list is short — the
        engine catches this once, evicts prefix-cache entries, and retries
        before giving up. With ``tenant`` set, the blocks are charged to
        that tenant; exceeding its quota raises
        :class:`TenantQuotaExceeded` (the engine then evicts that tenant's
        own prefix entries and retries)."""
        if tenant is not None:
            quota = self._quota.get(tenant)
            used = self._tenant_used.get(tenant, 0)
            if quota is not None and used + n > quota:
                raise TenantQuotaExceeded(
                    f"tenant {tenant!r} needs {n} KV blocks with {used}/"
                    f"{quota} quota blocks already owned — raise "
                    "serve.tenant_quota_blocks or shed this tenant's load"
                )
        if n > len(self._free):
            raise BlockPoolExhausted(
                f"need {n} KV blocks, {len(self._free)} free "
                f"({self.blocks_in_use}/{self.max_blocks - 1} in use) — "
                "raise engine.max_kv_blocks or shrink the slot batch"
            )
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._refcount[b] = 1
        if tenant is not None:
            for b in out:
                self._owner[b] = tenant
            self._tenant_used[tenant] = self._tenant_used.get(tenant, 0) + n
        self.high_water = max(self.high_water, self.blocks_in_use)
        return out

    def retain(self, blocks: Iterable[int]) -> None:  # acquires: kv-block-ref(arg)
        """One more holder for already-allocated blocks (prefix-cache hit)."""
        for b in blocks:
            if b not in self._refcount:
                raise ValueError(f"retain of unallocated block {b}")
            self._refcount[b] += 1

    def release(self, blocks: Iterable[int]) -> List[int]:  # releases: kv-block-ref(arg)
        """Drop one ref per block; returns the blocks that became free."""
        freed: List[int] = []
        for b in blocks:
            count = self._refcount.get(b)
            if count is None:
                raise ValueError(f"release of unallocated block {b}")
            if count == 1:
                del self._refcount[b]
                self._free.append(b)
                freed.append(b)
                owner = self._owner.pop(b, None)
                if owner is not None:
                    self._tenant_used[owner] -= 1
            else:
                self._refcount[b] = count - 1
        return freed
