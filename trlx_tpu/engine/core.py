"""The unified generation Engine: one interface over the repo's generation
paths, backed by a dense per-slot KV cache or the paged block pool.

Three generation paths used to live inside trainers: serial ``generate``
(ops/sampling.py), the PR-2 rollout pipeline (host overlap of an unchanged
serial decode), and the PR-3 slot-refill continuous-batching engine
(pipeline/continuous_batching.py). This module is their common home:

- :class:`SerialEngine` — plain batch generate behind the Engine
  interface. The dense serial path itself is untouched (it is the
  bit-equivalence reference every other path is tested against).
- :class:`ContinuousEngine` — the slot-refill engine (queue → refill →
  segment decode → harvest), generalized over the KV backend:

  * **dense** (default): the PR-3 per-slot ``[B, S]`` cache, byte-for-byte.
  * **paged** (``fns.paged`` set): KV lives in a block pool with per-slot
    block tables (``ops/paged_kv.py``). This engine owns the host half:
    a refcounted :class:`~trlx_tpu.engine.allocator.BlockAllocator` and
    lazy per-segment growth, so the pool's high-water tracks *live
    tokens* instead of ``slots × max_length``; and optionally a
    :class:`~trlx_tpu.engine.prefix_cache.PrefixCache` so rows whose
    padded prompts share committed full blocks prefill only their
    unshared suffix (GRPO groups, repeated eval prompts).

Determinism and bit-parity are inherited from the device half
(``ops/slot_refill.py``): prompts are assigned to slots in submission
order, harvested in slot order, and every sequence's tokens / logprobs /
values / mask are bit-identical to plain ``generate`` under per-row RNG —
for the dense AND paged backends, with and without prefix hits
(``tests/test_engine.py``, ``tests/test_continuous_batching.py``).

Utilization accounting (docs/PERFORMANCE.md): every decode step costs
``B`` slot-steps on device; only live slots produce real tokens.
``slot_utilization`` = live ÷ total slot-steps; ``padded_decode_frac`` is
its complement. The paged backend adds block-pool and prefix-cache gauges
(``engine/*``, ``memory/kv_cache_bytes``) — registered in
``tests/test_metric_names.py``.

Thread affinity: engines are single-threaded by design — exactly ONE
thread of control calls ``enqueue_prompts``/``step`` over an engine's
lifetime (the trainer's main thread, or the serve pump thread that owns a
serving engine exclusively — ``trlx_tpu/serve/server.py``); the rollout
pipeline worker and the HTTP handler threads see nothing but harvested
numpy copies handed over through locked serve-side buffers. If shared
mutable state is ever introduced here, annotate it ``# guarded-by:
<lock>`` so graftlint's lock-discipline pass (docs/STATIC_ANALYSIS.md)
enforces the locking, as in ``rollout_pipeline.py``.

Serving extensions (docs/SERVING.md): requests carry an optional tenant
(prefix-cache namespace + allocator quota) and a priority class —
``interactive`` outranks ``eval`` outranks ``actor`` at admission, and
queued higher-class traffic preempts still-prefilling lower-class slots
at step boundaries (the chunked-prefill scheduler is the seam: committed
prompt chunks are inserted into the tenant's radix chain before the slot
is vacated, so preempted work re-lands as prefix hits). An attached
:class:`~trlx_tpu.serve.tiering.HostTier` re-lands evicted prefix blocks
from host RAM instead of re-prefilling them.
"""

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from trlx_tpu.engine.allocator import (
    BlockAllocator,
    BlockPoolExhausted,
    TenantQuotaExceeded,
)
from trlx_tpu.engine.prefix_cache import PrefixCache
from trlx_tpu.ops.paged_kv import block_bytes, kv_bytes, num_table_blocks

__all__ = [
    "CompletedSequence",
    "EngineStats",
    "Engine",
    "SerialEngine",
    "ContinuousEngine",
    "SERVE_CLASSES",
]

# Priority classes, best-first (docs/SERVING.md): interactive user traffic
# outranks eval sweeps outranks the trainer's own actor batches. Admission
# pops the best-ranked queued request (FIFO within a class by submission
# index), so the rank table IS the scheduling policy.
SERVE_CLASSES = ("interactive", "eval", "actor")
_CLASS_RANK = {k: i for i, k in enumerate(SERVE_CLASSES)}
_DEFAULT_RANK = _CLASS_RANK["actor"]


@dataclass
class CompletedSequence:
    """One finished rollout, harvested from its slot."""

    index: int  # global submission index (queue order)
    prompt_ids: np.ndarray  # [P] left-padded prompt
    prompt_mask: np.ndarray  # [P]
    tokens: np.ndarray  # [N] response tokens (pad after eos)
    logprobs: np.ndarray  # [N] behavior logprobs
    values: np.ndarray  # [N] value-head outputs (0 if no head)
    mask: np.ndarray  # [N] 1 on real response tokens (incl. eos)
    meta: Any = None  # caller payload (e.g. GRPO group id)
    # request lifecycle timestamps (perf_counter; 0.0 = untracked): the
    # per-request spans the serve SLO metrics derive queue-wait/TTFT/TPOT
    # from (trlx_tpu/serve/metrics.py) — same instants the tracer's
    # engine/queue_wait → prefill → decode spans are built on
    t_enqueue: float = 0.0
    t_prefill0: float = 0.0
    t_prefill1: float = 0.0
    t_harvest: float = 0.0


@dataclass
class _Request:
    index: int
    input_ids: np.ndarray  # [P]
    attention_mask: np.ndarray  # [P]
    key: np.ndarray  # [2] per-row RNG chain start
    meta: Any = None
    # lifecycle timestamps (perf_counter) for the per-request trace spans:
    # queue wait = enqueue → first prefill work, prefill = the refill (or
    # first-through-last chunk) program calls, decode = prefill end →
    # harvest
    t_enqueue: float = 0.0
    t_refill0: float = 0.0
    t_refill1: float = 0.0
    # chunked prefill: next prompt column to prefill (None = prefill done
    # or not chunked); the engine advances one chunk per step
    prefill_pos: Optional[int] = None
    # serving extensions: prefix-cache namespace + quota identity, and the
    # priority class admission/preemption schedule on (docs/SERVING.md)
    tenant: Optional[str] = None
    klass: str = "actor"


@dataclass
class EngineStats:
    """Aggregate slot / block / prefix accounting over one engine lifetime."""

    segments: int = 0
    decode_steps: int = 0  # device decode steps executed
    slot_steps: int = 0  # decode_steps × B
    live_slot_steps: int = 0  # slot-steps spent on live rows
    refill_prefills: int = 0  # refill-program invocations
    refilled_rows: int = 0  # prompts placed into slots
    harvested: int = 0
    decode_s: float = 0.0  # wall time inside decode segments
    refill_s: float = 0.0  # wall time inside refill prefills
    queue_wait_s: float = 0.0  # summed enqueue→refill wait over requests
    # per-request queue waits (one sample per admitted request): the
    # p50/p95 the trainer gauges and the serve SLO metrics share — the
    # aggregate sum above cannot answer "how long does a request wait",
    # which is the admission-control question (docs/SERVING.md)
    queue_wait_samples: List[float] = field(default_factory=list)
    # KV memory (docs/PERFORMANCE.md): the persistent cache allocation, and
    # for the paged backend the live-token-scaled high-water
    kv_cache_bytes: int = 0  # dense cache / paged pool allocation
    kv_blocks_total: int = 0  # 0 = dense backend
    kv_blocks_in_use: int = 0  # high-water blocks simultaneously held
    kv_bytes_high_water: int = 0  # blocks_in_use × per-block bytes (paged)
    # paged decode compute path: True = in-place Pallas kernel decode
    # (engine.decode_kernel: pallas), False = the gather/scatter reference
    decode_kernel_pallas: bool = False
    # paged prefill compute path: True = in-place Pallas prefill kernel
    # (engine.prefill_kernel: pallas), False = gather-prefill-scatter
    prefill_kernel_pallas: bool = False
    # analytic bytes the refill prefills move through transient dense
    # views: gather = pool → dense view on program entry, scatter = written
    # span → pool on exit. Exactly 0 under the in-place prefill kernel —
    # the acceptance number of the ENGINE_PREFILL A/B (docs/PERFORMANCE.md)
    refill_gather_bytes: int = 0
    refill_scatter_bytes: int = 0
    # chunked-prefill scheduling (engine.prefill_chunk)
    prefill_chunk_calls: int = 0  # mid-chunk program invocations
    # decode-stall accounting: wall-seconds of prefill work that ran while
    # >= 1 seeded (decoding) slot sat waiting — one sample per stalling
    # prefill event, so p50/p95/max bound how long a live decode slot can
    # be held up by prompt admission (the number chunked prefill shrinks)
    decode_stall_s: float = 0.0
    decode_stall_samples: List[float] = field(default_factory=list)
    # prefix cache
    prefix_enabled: bool = False
    prefix_lookup_blocks: int = 0
    prefix_hit_blocks: int = 0
    prefix_tokens_saved: int = 0  # prompt columns NOT re-prefilled
    prefix_evicted_blocks: int = 0
    prefill_tokens: int = 0  # prompt columns actually prefilled
    # host-RAM tiering (trlx_tpu/serve/tiering.py): evicted prefix blocks
    # re-landed from the host pool instead of re-prefilled
    host_tier_enabled: bool = False
    host_tier_hit_blocks: int = 0
    host_tier_tokens_saved: int = 0  # prompt columns re-landed, not computed
    # priority scheduling: still-prefilling lower-class slots vacated for
    # queued higher-class traffic (requeued, committed chunks preserved)
    preempted_rows: int = 0
    # speculative decode segments (engine.speculative = k > 0): deltas of
    # the device-cumulative spec counters over this collection — verify
    # rounds run, live row-rounds, draft tokens accepted, tokens committed
    spec_gamma: int = 0
    spec_rounds: int = 0
    spec_live_rounds: int = 0
    spec_accepted: int = 0
    spec_committed: int = 0
    # spec verify compute path: True = in-place multi-position verify
    # kernel (engine.decode_kernel: pallas with engine.speculative),
    # False = the gather → shared round → scatter reference
    spec_verify_kernel_pallas: bool = False
    # harvest-side generation canary (observability/health.py gen_canary):
    # per-sequence generated lengths, and adjacent repeated-token pairs —
    # the cheap on-harvest signal for degenerate looping generations
    gen_len_samples: List[float] = field(default_factory=list)
    repeat_pairs: int = 0  # adjacent equal-token pairs in responses
    repeat_pairs_total: int = 0  # adjacent in-response pairs observed

    @property
    def slot_utilization(self) -> float:
        if self.slot_steps == 0:
            return 0.0
        return self.live_slot_steps / self.slot_steps

    @property
    def padded_decode_frac(self) -> float:
        if self.slot_steps == 0:
            return 0.0
        return 1.0 - self.slot_utilization

    @property
    def prefix_hit_rate(self) -> float:
        if self.prefix_lookup_blocks == 0:
            return 0.0
        return self.prefix_hit_blocks / self.prefix_lookup_blocks

    @property
    def spec_acceptance_rate(self) -> float:
        """Fraction of proposed draft tokens accepted, over live
        row-rounds (each proposes ``spec_gamma``)."""
        if self.spec_live_rounds == 0:
            return 0.0
        return self.spec_accepted / (
            self.spec_live_rounds * max(self.spec_gamma, 1)
        )

    @property
    def spec_tokens_per_round(self) -> float:
        """Committed tokens per live row-round ∈ [1, gamma+1] — the
        decode-throughput multiplier speculation buys."""
        if self.spec_live_rounds == 0:
            return 0.0
        return self.spec_committed / self.spec_live_rounds

    def _stall_pct(self, q: float) -> float:
        if not self.decode_stall_samples:
            return 0.0
        return float(np.percentile(np.asarray(self.decode_stall_samples), q))

    def _queue_wait_pct(self, q: float) -> float:
        if not self.queue_wait_samples:
            return 0.0
        return float(np.percentile(np.asarray(self.queue_wait_samples), q))

    @property
    def queue_wait_p50(self) -> float:
        return self._queue_wait_pct(50.0)

    @property
    def queue_wait_p95(self) -> float:
        return self._queue_wait_pct(95.0)

    @property
    def decode_stall_p50(self) -> float:
        return self._stall_pct(50.0)

    @property
    def decode_stall_p95(self) -> float:
        return self._stall_pct(95.0)

    @property
    def decode_stall_max(self) -> float:
        if not self.decode_stall_samples:
            return 0.0
        return float(max(self.decode_stall_samples))

    def note_harvest(self, tokens: np.ndarray, mask: np.ndarray) -> None:
        """Fold one harvested [B, N] (or [N]) response block into the
        generation canary: per-row generated lengths and the repeated
        adjacent-token fraction. Host numpy on already-fetched arrays."""
        tokens = np.atleast_2d(np.asarray(tokens))
        mask = np.atleast_2d(np.asarray(mask, np.float32))
        lens = mask.sum(axis=1)
        self.gen_len_samples.extend(float(n) for n in lens)
        if tokens.shape[1] > 1:
            pair_mask = mask[:, 1:] * mask[:, :-1]
            self.repeat_pairs += int(
                ((tokens[:, 1:] == tokens[:, :-1]) * pair_mask).sum()
            )
            self.repeat_pairs_total += int(pair_mask.sum())

    @property
    def repetition_frac(self) -> float:
        if self.repeat_pairs_total == 0:
            return 0.0
        return self.repeat_pairs / self.repeat_pairs_total

    def _gen_len_pct(self, q: float) -> float:
        if not self.gen_len_samples:
            return 0.0
        return float(np.percentile(np.asarray(self.gen_len_samples), q))

    def metrics(self) -> Dict[str, float]:
        """The observability-layer gauges (registered in
        ``tests/test_metric_names.py``; see docs/OBSERVABILITY.md)."""
        stats: Dict[str, float] = {}
        stats["throughput/slot_utilization"] = self.slot_utilization
        stats["rollout/padded_decode_frac"] = self.padded_decode_frac
        stats["rollout/refill_prefills"] = float(self.refill_prefills)
        stats["rollout/refilled_rows"] = float(self.refilled_rows)
        stats["rollout/segments"] = float(self.segments)
        stats["engine/queue_wait_s"] = float(self.queue_wait_s)
        # per-request queue-wait percentiles: the admission-control number —
        # the serve SLO check and the trainer share these samples
        stats["engine/queue_wait_p50"] = self.queue_wait_p50
        stats["engine/queue_wait_p95"] = self.queue_wait_p95
        stats["memory/kv_cache_bytes"] = float(self.kv_cache_bytes)
        # decode-stall percentiles (docs/PERFORMANCE.md "Chunked prefill"):
        # how long live decode slots waited on prefill work — the measured
        # number behind the chunked-prefill scheduling claim
        stats["rollout/decode_stall_p50"] = self.decode_stall_p50
        stats["rollout/decode_stall_p95"] = self.decode_stall_p95
        stats["rollout/decode_stall_max"] = self.decode_stall_max
        stats["rollout/prefill_chunks"] = float(self.prefill_chunk_calls)
        # generation canary (observability/health.py): length percentiles
        # and repeated-token fraction over everything harvested so far
        if self.gen_len_samples:
            stats["rollout/gen_len_p50"] = self._gen_len_pct(50.0)
            stats["rollout/gen_len_p95"] = self._gen_len_pct(95.0)
            stats["rollout/repetition_frac"] = self.repetition_frac
        if self.kv_blocks_total:
            stats["engine/kv_blocks_in_use"] = float(self.kv_blocks_in_use)
            stats["engine/block_pool_occupancy"] = self.kv_blocks_in_use / max(
                self.kv_blocks_total, 1
            )
            # which decode/prefill compute the programs ran — an A/B
            # artifact (or a dashboard) can tell kernel from gather runs
            # without config archaeology
            stats["engine/decode_kernel_pallas"] = float(
                self.decode_kernel_pallas
            )
            stats["engine/prefill_kernel_pallas"] = float(
                self.prefill_kernel_pallas
            )
            # the refill gather/scatter tax, measured: 0 under the
            # in-place prefill kernel
            stats["engine/refill_gather_bytes"] = float(
                self.refill_gather_bytes
            )
            stats["engine/refill_scatter_bytes"] = float(
                self.refill_scatter_bytes
            )
        if self.prefix_enabled:
            stats["engine/prefix_hit_rate"] = self.prefix_hit_rate
            stats["engine/prefix_tokens_saved"] = float(self.prefix_tokens_saved)
        if self.preempted_rows:
            stats["engine/preempted_rows"] = float(self.preempted_rows)
        if self.host_tier_enabled:
            # host-tier effectiveness: prompt columns whose KV came back
            # over PCIe instead of through a prefill forward
            stats["engine/host_tier_hit_blocks"] = float(self.host_tier_hit_blocks)
            stats["engine/host_tier_tokens_saved"] = float(
                self.host_tier_tokens_saved
            )
        if self.spec_gamma:
            # speculative decode segments: how much of the draft's work the
            # target kept, and the per-round throughput multiplier
            stats["engine/spec_acceptance_rate"] = self.spec_acceptance_rate
            stats["engine/spec_tokens_per_round"] = self.spec_tokens_per_round
            stats["rollout/spec_rounds"] = float(self.spec_rounds)
            # which verify compute the rounds ran — same contract as the
            # decode/prefill kernel gauges above
            stats["engine/spec_verify_kernel_pallas"] = float(
                self.spec_verify_kernel_pallas
            )
        return stats


class Engine:
    """The minimal contract every generation engine implements: feed
    prompts with per-row RNG chain starts, turn the crank, collect
    individually completed sequences. Trainers talk only to this surface
    (``_collect_continuous``; ``generate`` routes through
    :class:`SerialEngine`), so backends — dense, paged, and eventually the
    disaggregated actor fleet (ROADMAP item 1) — swap under one interface.
    """

    stats: EngineStats

    def enqueue_prompts(
        self,
        input_ids: np.ndarray,
        attention_mask: np.ndarray,
        keys: np.ndarray,
        metas: Optional[List[Any]] = None,
    ) -> None:
        raise NotImplementedError

    def step(self) -> List[CompletedSequence]:
        raise NotImplementedError

    @property
    def busy(self) -> bool:
        raise NotImplementedError

    def run(self) -> List[CompletedSequence]:
        """Drain queue + slots to completion (small-scale convenience; the
        trainers interleave :meth:`step` with downstream scoring instead)."""
        out: List[CompletedSequence] = []
        while self.busy:
            out.extend(self.step())
        return out


class SerialEngine(Engine):
    """Plain batch generate behind the Engine interface.

    Wraps a jitted ``fn(params, input_ids, attention_mask, rng)`` — the
    trainers' serial rollout program, UNCHANGED (it is the bit-equivalence
    reference). The streaming surface buffers whole chunks with the rng
    they were submitted under, so each :meth:`step` reproduces exactly one
    serial ``generate`` call.
    """

    def __init__(self, generate_fn: Callable, params: Any, pad_token_id: int):
        self._fn = generate_fn
        self.params = params
        self.pad_token_id = int(pad_token_id)
        self._chunks: deque = deque()
        self._submitted = 0
        self.stats = EngineStats()

    def generate(self, input_ids, attention_mask, rng):
        """The batch-synchronous path ``TPUBaseTrainer.generate`` routes
        through — returns whatever the wrapped program returns (a
        GenerationOutput, or ``(output, stats)`` for the speculative
        sampler)."""
        return self._fn(self.params, input_ids, attention_mask, rng)

    def enqueue_prompts(self, input_ids, attention_mask, keys=None, metas=None):
        raise NotImplementedError(
            "SerialEngine decodes whole chunks under one rng: use "
            "submit_chunk(input_ids, attention_mask, rng) (per-row keys "
            "are a continuous-batching concept)"
        )

    def submit_chunk(self, input_ids, attention_mask, rng, metas=None) -> None:
        input_ids = np.asarray(input_ids, np.int32)
        attention_mask = np.asarray(attention_mask, np.int32)
        idx = list(range(self._submitted, self._submitted + input_ids.shape[0]))
        self._submitted += input_ids.shape[0]
        self._chunks.append((idx, input_ids, attention_mask, rng, metas))

    @property
    def busy(self) -> bool:
        return bool(self._chunks)

    def step(self) -> List[CompletedSequence]:
        if not self._chunks:
            return []
        idx, ids, mask, rng, metas = self._chunks.popleft()
        t0 = time.perf_counter()
        out = self.generate(ids, mask, rng)
        if type(out) is tuple:  # speculative sampler: (output, stats)
            out = out[0]
        host = {
            "tokens": np.asarray(out.response_tokens),
            "logprobs": np.asarray(out.response_logprobs),
            "values": np.asarray(out.response_values),
            "mask": np.asarray(out.response_mask),
        }
        self.stats.decode_s += time.perf_counter() - t0
        self.stats.segments += 1
        n = len(idx)
        steps = int(host["mask"].sum(axis=1).max()) if n else 0
        self.stats.decode_steps += steps
        self.stats.slot_steps += steps * n
        self.stats.live_slot_steps += int(host["mask"].sum())
        self.stats.harvested += n
        self.stats.note_harvest(host["tokens"], host["mask"])
        return [
            CompletedSequence(
                index=idx[i],
                prompt_ids=ids[i],
                prompt_mask=mask[i],
                tokens=host["tokens"][i],
                logprobs=host["logprobs"][i],
                values=host["values"][i],
                mask=host["mask"][i],
                meta=metas[i] if metas is not None else None,
            )
            for i in range(n)
        ]


class ContinuousEngine(Engine):
    """Slot-refill decode over a fixed ``[B]`` slot batch.

    ``fns`` are the compiled programs from
    :func:`trlx_tpu.ops.slot_refill.make_slot_refill_fns` — their
    ``paged`` field selects the KV backend; ``span`` is an optional
    ``Observability.span``-shaped callable — each segment runs under a
    fenced ``rollout/segment`` span so the trace shows device-true decode
    time per segment. ``tracer`` (an ``Observability.tracer``) additionally
    emits per-request lifecycle spans at harvest — ``engine/queue_wait`` →
    ``engine/prefill`` → ``engine/decode`` on a per-slot track — so a stall
    is attributable to one row, not smeared over the batch. ``prefix_cache``
    (paged backend only) turns on shared-prefix prefill skipping.

    ``prefill_chunk`` (paged backend only, ``engine.prefill_chunk``) turns
    on chunked-prefill *scheduling*: admitted prompts prefill one
    fixed-size span per :meth:`step`, interleaved with decode segments, so
    a long prompt can never stall live decode slots longer than one
    chunk's prefill (the stall mode PipelineRL, arXiv:2509.19128,
    identifies for long-sequence RL generation; the
    ``rollout/decode_stall_*`` gauges measure it). Spans align to absolute
    multiples of the chunk size, mid-prompt spans run cache-only chunk
    programs, the final span is the ordinary refill program (hit = its
    start) — harvested sequences stay bit-identical to the monolithic
    path across chunk sizes (``tests/test_paged_attention.py``,
    ``tests/test_engine.py``). Each per-request chunk additionally lands
    as an ``engine/prefill_chunk`` span on the slot's trace track.

    Speculative decode segments (``fns.speculative = k > 0``, paged
    backend): each segment runs draft-propose → paged-verify → accept
    ROUNDS instead of single-token steps, committing 1..k+1 tokens per
    live row per round — ``params`` is then a ``(target, draft)`` tuple
    (swapped atomically by :meth:`swap_params`), harvested rows stay
    bit-identical to solo ``ops/speculative.py`` runs per row
    (``tests/test_spec_engine.py``), and the ``engine/spec_*`` gauges
    report acceptance. Admission, chunked prefill, prefix-cache hits and
    insertion are UNCHANGED — speculation only replaces the decode
    segment's inner loop.
    """

    def __init__(
        self,
        fns: Any,  # SlotRefillFns
        params: Any,
        pad_token_id: int,
        span: Optional[Callable[..., Any]] = None,
        tracer: Any = None,
        prewarm: bool = True,
        prefix_cache: bool = False,
        prefix_capacity_blocks: int = 0,
        prefill_chunk: int = 0,
    ):
        import jax.numpy as jnp  # deferred: host module, device state here only

        self._jnp = jnp
        self.fns = fns
        self.params = params
        self.pad_token_id = int(pad_token_id)
        self._span = span
        self._tracer = tracer
        self.state = fns.init_state()
        self.B = fns.batch_size
        self.P = fns.prompt_len
        self.N = fns.max_new_tokens
        self._queue: deque = deque()
        self._slots: List[Optional[_Request]] = [None] * self.B
        # True once the slot's FINAL prefill span ran (the refill program
        # scattered its SlotState row: logits seeded, done=False). Chunked
        # prefill leaves a slot unseeded — and hence outside harvest and
        # decode-block growth — until its last span lands.
        self._seeded: List[bool] = [False] * self.B
        self._submitted = 0
        self.stats = EngineStats()
        self._chunk = int(prefill_chunk)
        if self._chunk < 0:
            raise ValueError(f"prefill_chunk {self._chunk} must be >= 0")
        # serving extensions (all default-off; single-threaded like the
        # rest of the engine — the serve pump thread owns them):
        # host-RAM tier of evicted prefix blocks (attach_host_tier)
        self.host_tier: Any = None
        # slots only interactive-class requests may take, so a saturating
        # batch workload cannot push interactive TTFT past one admission
        self.reserve_slots = 0
        # requests that failed admission-side (tenant quota): the owner
        # drains these after step() — trainer traffic never lands here
        self.failed: deque = deque()

        self.spec = getattr(fns, "paged", None)
        # speculative decode segments (ops/slot_refill.py speculative=k):
        # params become a (target, draft) tuple, buffers widen to
        # N + gamma + 1, caches to S = P + N + gamma, and rows advance
        # VARIABLE amounts per round — the per-slot step counters below
        # track the true committed lengths instead of a uniform bound
        self._gamma = int(getattr(fns, "speculative", 0) or 0)
        self._S = self.P + self.N + self._gamma
        self.stats.spec_gamma = self._gamma
        # device spec counters are cumulative over the fns-state lifetime;
        # per-collection stats are deltas against this snapshot
        self._spec_base = {
            "rounds": 0, "accepted": 0, "live_rounds": 0, "committed": 0
        }
        self.allocator: Optional[BlockAllocator] = None
        self.prefix: Optional[PrefixCache] = None
        if self.spec is not None:
            S = self._S
            self._bs = self.spec.block_size
            self._TB = num_table_blocks(S, self._bs)
            self.allocator = BlockAllocator(self.spec.max_blocks)
            if prefix_cache:
                self.prefix = PrefixCache(self._bs, prefix_capacity_blocks)
                self.stats.prefix_enabled = True
            # host mirror of the device block table — authoritative between
            # programs (refill programs apply the same rows on device;
            # segment-growth pushes the whole mirror)
            self._tables = np.zeros((self.B, self._TB), np.int32)
            self._row_blocks: List[Optional[List[int]]] = [None] * self.B
            # leading table entries with real (allocated) backing per slot
            self._alloc_upto = [0] * self.B
            # upper bound on each slot's decode step (segments survived)
            self._steps_bound = [0] * self.B
            self.stats.kv_blocks_total = self.spec.max_blocks - 1
            # gauges reflect the compute that actually RUNS: on builds
            # without the Mosaic backend the kernels fall back to their
            # gather references (ops/pallas_utils.has_pallas_tpu), and
            # reporting kernel=1 / gather bytes=0 there would stamp wrong
            # acceptance numbers into an A/B artifact
            from trlx_tpu.ops.pallas_utils import has_pallas_tpu

            self.stats.decode_kernel_pallas = (
                getattr(fns, "decode_kernel", "xla") == "pallas"
                and has_pallas_tpu()
            )
            self.stats.prefill_kernel_pallas = (
                getattr(fns, "prefill_kernel", "xla") == "pallas"
                and has_pallas_tpu()
            )
            self.stats.spec_verify_kernel_pallas = bool(
                self._gamma
                and getattr(fns, "decode_kernel", "xla") == "pallas"
                and has_pallas_tpu()
            )
            self._block_bytes = block_bytes(self.state.cache)
            # per-cache-column bytes (all layers, k+v): the unit of the
            # analytic refill gather/scatter accounting
            self._col_bytes = self._block_bytes / max(self._bs, 1)
        elif prefix_cache:
            raise ValueError(
                "engine.prefix_cache requires the paged KV backend "
                "(engine.backend: paged) — dense per-slot caches cannot "
                "share blocks"
            )
        elif self._chunk:
            raise ValueError(
                "engine.prefill_chunk requires the paged KV backend "
                "(engine.backend: paged) — the chunk programs commit "
                "prompt spans through the block table"
            )
        self.stats.kv_cache_bytes = kv_bytes(self.state.cache)
        if self._gamma:
            # the draft's dense [B, S] cache is persistent engine state too
            self.stats.kv_cache_bytes += kv_bytes(self.state.d_cache)
        # identity of the params the pool's committed KV (and hence every
        # prefix-cache entry) was computed under — a different params tree
        # invalidates all cached KV (begin_collection flushes)
        self._kv_params = params
        # memoized version counter for the weight-sync path: per-segment
        # swap checks compare one int instead of adopting/flushing on every
        # fresh params object (each publish is a new copy, so the identity
        # test alone would false-negative and flush a still-valid cache)
        self._params_version: Optional[int] = None
        if prewarm:
            # once per SlotRefillFns (the fns — and their compiled bucket
            # programs — outlive this engine via the trainer's program
            # cache; later engines skip straight through)
            self.state = self.fns.prewarm(self.params, self.state)

    def begin_collection(self, params: Any, version: Optional[int] = None) -> None:
        """Reuse this engine for a fresh collection: reset the
        per-collection stats, adopt the (possibly updated) policy params,
        and drop any leftovers of an aborted run. Cached prefix KV is
        valid ONLY under the params it was computed with — a new params
        tree (the policy trained in between) flushes the prefix cache;
        identical params (repeated eval, back-to-back collections without
        an update) keep it warm, which is where cross-collection prefill
        savings come from. ``version`` (the weight-sync path) memoizes a
        cheap counter: a matching version skips the flush even when the
        params object is a fresh copy of the same weights."""
        self._queue.clear()
        self.failed.clear()
        for slot in range(self.B):
            if self._slots[slot] is None:
                continue
            # aborted-collection leftovers: free the slot (and its blocks —
            # a refill that died inside _prepare_row assigned the slot but
            # never wrote its block list, hence the None guard)
            if self.spec is not None:
                if self._row_blocks[slot] is not None:
                    self.allocator.release(self._row_blocks[slot])
                self._row_blocks[slot] = None
                self._alloc_upto[slot] = 0
                self._steps_bound[slot] = 0
            self._slots[slot] = None
            self._seeded[slot] = False
        if not bool(np.asarray(self.state.done).all()):
            # freeze any still-decoding device rows from the aborted run
            self.state = self.state._replace(
                done=self._jnp.ones((self.B,), bool)
            )
        self._adopt_params(params, version)
        self.stats = EngineStats(
            kv_cache_bytes=self.stats.kv_cache_bytes,
            prefix_enabled=self.stats.prefix_enabled,
            host_tier_enabled=self.stats.host_tier_enabled,
            kv_blocks_total=self.stats.kv_blocks_total,
            decode_kernel_pallas=self.stats.decode_kernel_pallas,
            prefill_kernel_pallas=self.stats.prefill_kernel_pallas,
            spec_verify_kernel_pallas=self.stats.spec_verify_kernel_pallas,
            spec_gamma=self._gamma,
        )
        if self._gamma:
            self._spec_base = self._read_spec_counters()
        if self.allocator is not None:
            # per-collection high-water, not lifetime
            self.allocator.high_water = self.allocator.blocks_in_use

    @staticmethod
    def _same_params(a: Any, b: Any) -> bool:
        """Identity, element-wise over (target, draft) params tuples — the
        speculative engine's params often arrive as a freshly-built 2-tuple
        around the SAME trees every call, and the naked identity test would
        false-negative and flush a still-valid prefix cache."""
        if type(a) is tuple and type(b) is tuple and len(a) == len(b):
            return all(x is y for x, y in zip(a, b))
        return a is b

    def _params_changed(self, params: Any, version: Optional[int]) -> bool:
        """One int compare on the versioned weight-sync path, identity on
        the unversioned path — never a tree walk. The spec engine's
        (target, draft) tuple swaps ATOMICALLY: both trees arrive in one
        params object adopted at one segment boundary."""
        if version is not None and self._params_version is not None:
            return version != self._params_version
        return not self._same_params(params, self._kv_params)

    def _adopt_params(self, params: Any, version: Optional[int]) -> None:
        if self._params_changed(params, version):
            if self.prefix is not None:
                self.prefix.clear(self.allocator)
            if self.host_tier is not None:
                # spilled KV is valid only under the params that computed
                # it — exactly like the device-side entries just cleared
                self.host_tier.clear()
            self._kv_params = params
        self._params_version = version
        self.params = params

    def attach_host_tier(self, tier: Any) -> None:
        """Wire a :class:`~trlx_tpu.serve.tiering.HostTier` behind the
        prefix cache: evicted entries spill their block KV host-side, and
        admission re-lands host-resident chunks instead of re-prefilling.
        The tier is owned by this engine's (single) driving thread."""
        if self.prefix is None:
            raise ValueError(
                "host tiering requires the prefix cache "
                "(engine.prefix_cache: true) — only committed prefix "
                "entries ever spill"
            )
        self.host_tier = tier
        self.stats.host_tier_enabled = True
        self.prefix.spill = self._spill_entry

    def _spill_entry(self, entry: Any) -> None:
        """Prefix-cache eviction hook: copy the victim's block rows to the
        host pool before the cache drops its ref (committed KV is
        immutable, so the copy is valid even while a live row shares the
        block)."""
        self.host_tier.spill(entry.digest, self.state.cache.pool, entry.block)

    def swap_params(self, params: Any, version: Optional[int] = None) -> bool:
        """In-flight weight sync (docs/ASYNC_RL.md): adopt updated params
        MID-COLLECTION at a segment boundary. Live rows keep their KV (the
        sequence becomes a bounded param-version mixture — the behavior
        logprobs the sampler records stay exact), but cached *shared*
        prefix KV under the old params must never seed a future row's
        prefill: a changed version flushes the prefix cache, exactly like
        ``begin_collection``. Returns True when the params actually
        changed; a matching memoized version is a cheap no-op. With
        chunked prefill, a swap between a row's chunks makes its *prompt*
        KV a bounded param-version mixture too — same contract as live
        decode rows: the sampler's recorded behavior logprobs stay exact,
        the mixture is what actually generated the sequence."""
        if not self._params_changed(params, version):
            self._params_version = version if version is not None else self._params_version
            return False
        self._adopt_params(params, version)
        return True

    # -- feeding ---------------------------------------------------------

    def enqueue_prompts(
        self,
        input_ids: np.ndarray,  # [b, p] left-padded, p <= P
        attention_mask: np.ndarray,  # [b, p]
        keys: np.ndarray,  # [b, 2] per-row RNG chain starts
        metas: Optional[List[Any]] = None,
        tenant: Optional[str] = None,
        klass: str = "actor",
    ) -> None:
        """Queue a prompt batch. Rows narrower than the engine width are
        left-padded to ``P`` (bit-stream-neutral only when the caller also
        runs its reference ``generate`` at width ``P``); wider rows are an
        error — the KV cache was sized for ``P``. ``tenant`` scopes the
        batch's prefix-cache namespace and block quota; ``klass`` is its
        priority class (:data:`SERVE_CLASSES`) — the trainer's default
        ``actor`` keeps the pre-serving FIFO behavior when nothing of a
        better class is queued."""
        if klass not in _CLASS_RANK:
            raise ValueError(
                f"unknown priority class {klass!r}: expected one of "
                f"{SERVE_CLASSES}"
            )
        input_ids = np.asarray(input_ids, np.int32)
        attention_mask = np.asarray(attention_mask, np.int32)
        b, p = input_ids.shape
        if p > self.P:
            raise ValueError(
                f"prompt width {p} exceeds the engine's padded width {self.P}; "
                "size the engine from the widest prompt chunk (or pin the "
                "prompt loader's width with fixed_length)"
            )
        if p < self.P:
            pad = self.P - p
            input_ids = np.concatenate(
                [np.full((b, pad), self.pad_token_id, np.int32), input_ids], axis=1
            )
            attention_mask = np.concatenate(
                [np.zeros((b, pad), np.int32), attention_mask], axis=1
            )
        keys = np.asarray(keys)
        t_enqueue = time.perf_counter()
        for i in range(b):
            self._queue.append(
                _Request(
                    index=self._submitted,
                    input_ids=input_ids[i],
                    attention_mask=attention_mask[i],
                    key=keys[i],
                    meta=metas[i] if metas is not None else None,
                    t_enqueue=t_enqueue,
                    tenant=tenant,
                    klass=klass,
                )
            )
            self._submitted += 1

    # -- state -----------------------------------------------------------

    @property
    def pending(self) -> int:
        """Prompts queued but not yet in a slot."""
        return len(self._queue)

    @property
    def live(self) -> int:
        """Slots currently holding an unharvested sequence."""
        return sum(1 for r in self._slots if r is not None)

    @property
    def busy(self) -> bool:
        return self.live > 0 or self.pending > 0

    # -- paged-block bookkeeping ----------------------------------------

    def _alloc_blocks(self, n: int, tenant: Optional[str] = None) -> List[int]:  # acquires: kv-block-ref
        """Allocate with one eviction retry: on pool pressure, drop LRU
        prefix-cache entries (their blocks free unless a live row still
        shares them) before giving up. A quota'd tenant's pressure evicts
        ONLY that tenant's entries — another tenant's working set is never
        shed to admit this one (docs/SERVING.md)."""
        if n == 0:
            return []
        try:
            return self.allocator.alloc(n, tenant=tenant)
        except TenantQuotaExceeded:
            if self.prefix is None:
                raise
            quota = self.allocator.tenant_quota(tenant)
            headroom = max(
                (quota or 0) - self.allocator.tenant_blocks_in_use(tenant), 0
            )
            self.stats.prefix_evicted_blocks += self.prefix.evict(
                self.allocator, blocks_needed=n - headroom, tenant=tenant
            )
            # still over quota → the caller fails THIS request, not the engine
            return self.allocator.alloc(n, tenant=tenant)
        except BlockPoolExhausted:
            if self.prefix is not None:
                self.stats.prefix_evicted_blocks += self.prefix.evict(
                    self.allocator, blocks_needed=n - self.allocator.blocks_free
                )
                return self.allocator.alloc(n, tenant=tenant)  # exhausted again → caller's error
            raise

    def _note_block_usage(self) -> None:
        self.stats.kv_blocks_in_use = self.allocator.high_water
        self.stats.kv_bytes_high_water = (
            self.allocator.high_water * self._block_bytes
        )

    def _prepare_row(self, req: "_Request", slot: int) -> int:  # acquires: row-block-ref(object)
        """Assign blocks for one refilled row: shared prefix blocks from
        the cache (refcount++), host-tier re-lands for chunks beyond the
        device hit (spilled KV written back verbatim — bit-identical to a
        cold prefill by construction), fresh private blocks for the rest
        of the prompt region. Returns the row's hit length in cache
        columns (block-aligned, capped so at least one prompt column is
        always recomputed — the refill forward must produce last-position
        logits to seed the sampler)."""
        shared: List[int] = []
        cap = (self.P - 1) // self._bs
        if self.prefix is not None:
            shared = self.prefix.match(
                req.input_ids, req.attention_mask, tenant=req.tenant
            )
            shared = shared[:cap]
            # denominator = blocks a hit could ever cover — the cap above
            # always recomputes the last prompt block, so a fully warm
            # repeat prompt reaches hit_rate 1.0
            self.stats.prefix_lookup_blocks += cap
            self.stats.prefix_hit_blocks += len(shared)
        # retain the matched chain BEFORE allocating: _alloc_blocks may
        # evict prefix-cache entries under pool pressure, and a cache-only
        # ref on a just-matched block would let eviction free it and hand
        # it back as this row's writable "fresh" block (aliasing a shared
        # prefix position with a write target). With the row's ref held,
        # eviction only ever drops the cache's ref — the block survives.
        self.allocator.retain(shared)  # no-op for a cold miss (empty hit)
        relanded = self._reland_from_tier(req, len(shared), cap, shared)
        hit_chain = shared + relanded
        hit = len(hit_chain) * self._bs
        n_prompt_blocks = (self.P - 1) // self._bs + 1
        try:
            fresh = self._alloc_blocks(
                n_prompt_blocks - len(hit_chain), tenant=req.tenant
            )
        except (BlockPoolExhausted, TenantQuotaExceeded):
            self.allocator.release(hit_chain)  # no leak on the error path
            raise
        row = np.zeros(self._TB, np.int32)
        row[: len(hit_chain)] = hit_chain
        row[len(hit_chain) : n_prompt_blocks] = fresh
        self._tables[slot] = row
        self._row_blocks[slot] = hit_chain + fresh
        self._alloc_upto[slot] = n_prompt_blocks
        self._steps_bound[slot] = 0
        return hit

    def _reland_from_tier(
        self, req: "_Request", n_hit: int, cap: int, shared: List[int]
    ) -> List[int]:  # acquires: kv-block-ref
        """Probe the host tier for the consecutive chunks beyond the
        device hit; write each host-resident chunk's spilled KV into a
        fresh device block and commit it back into the tenant's radix
        chain (so siblings share it and the cache owns a ref, exactly like
        a prefilled block). Returns the re-landed blocks, row ref held."""
        if self.host_tier is None or self.prefix is None or n_hit >= cap:
            return []
        digests = self.prefix.chain_digests(
            req.input_ids, req.attention_mask, cap, tenant=req.tenant
        )
        run: List[bytes] = []
        for i in range(n_hit, min(cap, len(digests))):
            if not self.host_tier.probe(digests[i]):
                break
            run.append(digests[i])
        if not run:
            return []
        try:
            blocks = self._alloc_blocks(len(run), tenant=req.tenant)
        except (BlockPoolExhausted, TenantQuotaExceeded):
            return []  # the tier is an optimization: fall back to re-prefill
        pool = self.host_tier.reland_many(run, self.state.cache.pool, blocks)
        self.state = self.state._replace(
            cache=self.state.cache._replace(pool=pool)
        )
        self.prefix.insert(
            req.input_ids,
            req.attention_mask,
            shared + blocks,
            self.allocator,
            tenant=req.tenant,
        )
        self.stats.host_tier_hit_blocks += len(blocks)
        self.stats.host_tier_tokens_saved += len(blocks) * self._bs
        return blocks

    def _ensure_decode_blocks(self, segment_len: int) -> bool:
        """Grow each live row's table to cover the columns the next decode
        segment may write — lazy allocation is what makes the pool's
        high-water track live tokens. Returns True when any table changed
        (the mirror must be pushed to device)."""
        dirty = False
        for slot in range(self.B):
            if self._slots[slot] is None or not self._seeded[slot]:
                # still-prefilling slots decode nothing this segment — their
                # prompt blocks were assigned at admission, decode blocks
                # wait until the final span seeds them
                continue
            # a spec segment commits up to (gamma+1) tokens per round per
            # live row, bounded by the row hitting N
            per_seg = segment_len * (self._gamma + 1) if self._gamma else segment_len
            need_cols = self.P + min(
                self.N, self._steps_bound[slot] + per_seg
            )
            need_blocks = (need_cols - 1) // self._bs + 1
            have = self._alloc_upto[slot]
            if need_blocks > have:
                fresh = self._alloc_blocks(need_blocks - have)
                self._tables[slot, have:need_blocks] = fresh
                self._row_blocks[slot].extend(fresh)
                self._alloc_upto[slot] = need_blocks
                dirty = True
        return dirty

    def _push_tables(self) -> None:
        self.state = self.state._replace(
            cache=self.state.cache._replace(
                block_table=self._jnp.asarray(self._tables)
            )
        )

    # -- the slot-refill state machine -----------------------------------

    def _read_spec_counters(self) -> Dict[str, int]:
        """Fetch the device-cumulative spec counters (tiny scalars; the
        caller already blocked on the segment they were produced by)."""
        return {
            k: int(np.asarray(getattr(self.state, k)))
            for k in ("rounds", "accepted", "live_rounds", "committed")
        }

    def _decoding(self) -> int:
        """Slots holding a seeded (decoding or awaiting-harvest) sequence —
        the population a prefill event stalls."""
        return sum(
            1
            for s in range(self.B)
            if self._slots[s] is not None and self._seeded[s]
        )

    def _note_prefill_event(self, waiting: int, t0: float, t1: float) -> None:
        """Decode-stall accounting: one sample per prefill event that ran
        while ``waiting`` seeded slots sat idle (docs/PERFORMANCE.md
        "Chunked prefill") — under chunked scheduling no sample can exceed
        one chunk's prefill, which is the whole point."""
        self.stats.refill_s += t1 - t0
        if waiting > 0:
            self.stats.decode_stall_s += t1 - t0
            self.stats.decode_stall_samples.append(t1 - t0)

    def _note_refill_io(self, rows: int, gather_cols: int, span_cols: int) -> None:
        """Analytic bytes of the transient dense view a gather-flavor
        prefill program moves (pool → view on entry, written span → pool on
        exit). The in-place prefill kernel moves none — the measured 0 the
        ENGINE_PREFILL A/B commits."""
        if self.stats.prefill_kernel_pallas:
            return
        self.stats.refill_gather_bytes += int(rows * gather_cols * self._col_bytes)
        self.stats.refill_scatter_bytes += int(rows * span_cols * self._col_bytes)

    def _rank(self, req: "_Request") -> int:
        return _CLASS_RANK.get(req.klass, _DEFAULT_RANK)

    def _pop_next(self, only_interactive: bool = False) -> Optional["_Request"]:
        """Best-class-first, FIFO-within-class (by submission index) pop —
        a requeued preemption victim's lower index restores its original
        place in its class. With ``only_interactive`` (the reserve-slot
        guard) only rank-0 requests are eligible."""
        best_i = -1
        best_key = None
        for i, req in enumerate(self._queue):
            rank = self._rank(req)
            if only_interactive and rank > 0:
                continue
            key = (rank, req.index)
            if best_key is None or key < best_key:
                best_i, best_key = i, key
        if best_key is None:
            return None
        req = self._queue[best_i]
        del self._queue[best_i]
        return req

    def _preempt_slot(self, slot: int) -> None:  # releases: row-block-ref(object)
        """Vacate one still-prefilling slot: committed prompt chunks are
        inserted into the tenant's radix chain FIRST (insert retains the
        blocks, so the committed work survives the row's release and
        re-lands as a prefix hit on re-admission), then the row's block
        refs drop and the request returns to the queue."""
        req = self._slots[slot]
        pos = req.prefill_pos or 0
        if self.prefix is not None and pos >= self._bs:
            n_committed = min(pos // self._bs, (self.P - 1) // self._bs)
            self.prefix.insert(
                req.input_ids,
                req.attention_mask,
                list(self._tables[slot, :n_committed]),
                self.allocator,
                tenant=req.tenant,
            )
        self.allocator.release(self._row_blocks[slot])
        self._row_blocks[slot] = None
        self._alloc_upto[slot] = 0
        self._steps_bound[slot] = 0
        self._slots[slot] = None
        self._seeded[slot] = False
        req.prefill_pos = None
        self._queue.append(req)
        self.stats.preempted_rows += 1

    def _preempt_for_priority(self) -> None:
        """The preemption seam (docs/SERVING.md): queued higher-class
        requests that cannot find a free slot vacate still-prefilling
        lower-class slots at the step boundary. Seeded (decoding) slots
        are never preempted — their KV would be lost mid-sequence; the
        chunked-prefill scheduler makes prefilling slots cheap to vacate
        (at most one chunk of uncommitted work)."""
        if self.spec is None or not self._queue:
            return
        free = sum(1 for s in range(self.B) if self._slots[s] is None)
        waiting = sorted(self._rank(r) for r in self._queue)
        # worst class first, least-progressed first: lose the least work
        victims = sorted(
            (
                s
                for s in range(self.B)
                if self._slots[s] is not None and not self._seeded[s]
            ),
            key=lambda s: (
                -self._rank(self._slots[s]),
                self._slots[s].prefill_pos or 0,
            ),
        )
        for slot in victims:
            vrank = self._rank(self._slots[slot])
            demand = sum(1 for r in waiting if r < vrank)
            if demand <= free:
                continue  # free slots already cover the outranking demand
            waiting.append(vrank)
            self._preempt_slot(slot)
            free += 1

    def _admit(self) -> None:
        """Move queued prompts into free slots, best priority class first
        (FIFO within a class). Dense backend: the whole prompt prefills
        immediately (one grouped gather-prefill-scatter). Paged backend:
        blocks are assigned (prefix hits → shared, host-tier re-lands,
        rest fresh) and the row's ``prefill_pos`` starts at its hit; the
        actual prefill work runs in :meth:`_advance_prefill` — one span
        per step, so with ``prefill_chunk`` set a long prompt is admitted
        instantly but prefilled incrementally between decode segments.
        ``reserve_slots`` holds the last free slots for interactive-class
        traffic; a tenant whose quota cannot cover its prompt fails onto
        :attr:`failed` instead of failing the engine."""
        self._preempt_for_priority()
        free = deque(s for s in range(self.B) if self._slots[s] is None)
        if not free or not self._queue:
            return
        rows: List[_Request] = []
        slots: List[int] = []
        while free and self._queue:
            if self.reserve_slots > 0:
                non_interactive = sum(
                    1
                    for s in range(self.B)
                    if self._slots[s] is not None
                    and self._rank(self._slots[s]) > 0
                )
                only_interactive = (
                    non_interactive >= self.B - self.reserve_slots
                )
            else:
                only_interactive = False
            req = self._pop_next(only_interactive)
            if req is None:
                break
            slot = free.popleft()
            self._slots[slot] = req
            self._seeded[slot] = False
            rows.append(req)
            slots.append(slot)
        if not rows:
            return
        if self.spec is None:
            waiting = self._decoding()
            t0 = time.perf_counter()
            # gather-prefill-scatter: only the fresh rows run the prefill
            # (bucketed to a power of two inside refill_rows)
            self.state = self.fns.refill_rows(
                self.params,
                self.state,
                np.stack([r.input_ids for r in rows]),
                np.stack([r.attention_mask for r in rows]),
                np.asarray(slots, np.int32),
                np.stack([r.key for r in rows]),
            )
            t1 = time.perf_counter()
            self.stats.refill_prefills += 1
            self.stats.prefill_tokens += self.P * len(rows)
            self._note_prefill_event(waiting, t0, t1)
            for req, slot in zip(rows, slots):
                req.t_refill0 = t0
                req.t_refill1 = t1
                self._seeded[slot] = True
                self.stats.queue_wait_s += max(t0 - req.t_enqueue, 0.0)
                self.stats.queue_wait_samples.append(
                    max(t0 - req.t_enqueue, 0.0)
                )
            self.stats.refilled_rows += len(rows)
            return
        admitted = 0
        for req, slot in zip(rows, slots):
            try:
                hit = self._prepare_row(req, slot)
            except TenantQuotaExceeded as e:
                # the tenant's budget cannot cover this prompt even after
                # shedding its own prefix entries: fail THE REQUEST (the
                # serve frontend turns this into an error response), never
                # the engine — trainer traffic is unquoted and cannot land
                # here
                self._slots[slot] = None
                self._seeded[slot] = False
                self.failed.append((req, str(e)))
                continue
            admitted += 1
            pos0 = hit
            if self._chunk:
                # skip all-masked leading pad columns: they are never
                # attention-visible (slot mask 0 → exact-0.0 softmax
                # terms), so committing their K/V is pure waste — start
                # chunking at the chunk-grid point at or below the first
                # real column (the final span must stay non-empty, hence
                # the (P-1) clamp for degenerate all-pad rows)
                first_real = self.P - int(np.sum(req.attention_mask))
                pos0 = max(
                    hit,
                    min(
                        (first_real // self._chunk) * self._chunk,
                        ((self.P - 1) // self._chunk) * self._chunk,
                    ),
                )
            req.prefill_pos = pos0
            self.stats.prefix_tokens_saved += hit
        self.stats.refilled_rows += admitted
        self._note_block_usage()

    def _next_span(self, pos: int) -> int:
        """End column of the prefill span starting at ``pos``: the whole
        remaining prompt when chunking is off, else up to the next
        ABSOLUTE multiple of the chunk size — prompts admitted at
        different prefix-hit offsets converge onto one span grid after
        their first chunk, so sibling rows group into one program and the
        compiled-span variety stays bounded."""
        if not self._chunk:
            return self.P
        return min(self.P, (pos // self._chunk + 1) * self._chunk)

    def _advance_prefill(self) -> None:
        """Run ONE prefill span for every still-prefilling slot, grouped by
        identical (start, end): mid-prompt spans run the cache-only chunk
        program; a span reaching ``P`` runs the ordinary refill program
        with ``hit = start`` (columns below it are committed — by prefix
        hits, earlier chunks, or both) and seeds the slot for decode.
        Prefix-cache insertion stays strictly AFTER the program calls of
        the event, exactly like the monolithic refill."""
        pending = [
            (s, self._slots[s].prefill_pos)
            for s in range(self.B)
            if self._slots[s] is not None
            and self._slots[s].prefill_pos is not None
        ]
        if not pending:
            return
        waiting = self._decoding()
        by_span: Dict[tuple, List[int]] = {}
        for slot, pos in pending:
            by_span.setdefault((pos, self._next_span(pos)), []).append(slot)
        finished: List[int] = []
        for (start, end), slots in sorted(by_span.items()):
            rows = [self._slots[s] for s in slots]
            t0 = time.perf_counter()
            if end < self.P:
                self.state = self.fns.prefill_chunk_rows(
                    self.params,
                    self.state,
                    np.stack([r.input_ids for r in rows]),
                    np.stack([r.attention_mask for r in rows]),
                    np.stack([self._tables[s] for s in slots]),
                    start=start,
                    end=end,
                )
                self.stats.prefill_chunk_calls += 1
                # the chunk program's gather (start > 0) covers the full
                # S-wide view — key width matches the monolithic pass for
                # bit-parity (ops/slot_refill.py chunk-program docstring)
                self._note_refill_io(
                    len(rows),
                    self._S if start > 0 else 0,
                    end - start,
                )
            else:
                self.state = self.fns.refill_rows(
                    self.params,
                    self.state,
                    np.stack([r.input_ids for r in rows]),
                    np.stack([r.attention_mask for r in rows]),
                    np.asarray(slots, np.int32),
                    np.stack([r.key for r in rows]),
                    table_rows=np.stack([self._tables[s] for s in slots]),
                    hit=start,
                )
                self._note_refill_io(
                    len(rows),
                    self._S if start > 0 else 0,
                    self.P - start,
                )
                finished.extend(slots)
            t1 = time.perf_counter()
            self.stats.refill_prefills += 1
            self.stats.prefill_tokens += (end - start) * len(rows)
            self._note_prefill_event(waiting, t0, t1)
            for req, slot in zip(rows, slots):
                if req.t_refill0 == 0.0:
                    req.t_refill0 = t0
                    self.stats.queue_wait_s += max(t0 - req.t_enqueue, 0.0)
                    self.stats.queue_wait_samples.append(
                        max(t0 - req.t_enqueue, 0.0)
                    )
                if self._tracer is not None and end < self.P:
                    self._tracer.add_complete_event(
                        "engine/prefill_chunk", t0, t1,
                        track=f"engine/slot{slot}", index=req.index,
                        start=start, end=end,
                    )
                if end < self.P:
                    req.prefill_pos = end
                else:
                    req.prefill_pos = None
                    req.t_refill1 = t1
                    self._seeded[slot] = True
        if self.prefix is not None and finished:
            # commit only blocks a later match could USE: _prepare_row caps
            # hits at (P-1)//bs (the last prompt block is always
            # recomputed), so when P is block-aligned the P//bs-th entry
            # would be permanently pinned yet never shareable
            n_full = (self.P - 1) // self._bs
            for slot in finished:
                req = self._slots[slot]
                self.prefix.insert(
                    req.input_ids,
                    req.attention_mask,
                    list(self._tables[slot, :n_full]),
                    self.allocator,
                    tenant=req.tenant,
                )
        self._note_block_usage()

    def _harvest(self) -> List[CompletedSequence]:  # releases: row-block-ref(object)
        done = np.asarray(self.state.done)
        finished = [
            s
            for s in range(self.B)
            # unseeded (still-prefilling) slots read device done=True from
            # their empty SlotState row — they are not finished, they have
            # not started
            if self._slots[s] is not None and self._seeded[s] and done[s]
        ]
        if not finished:
            return []
        idx = self._jnp.asarray(np.asarray(finished, np.int32))
        rows = {
            # spec buffers are [B, N + gamma + 1] (block writes never
            # clip); the caller-visible response is always [N]
            name: getattr(self.state, name)[idx, : self.N]
            for name in ("tokens", "logprobs", "values", "mask")
        }
        # ship immediately: start the device→host copies without blocking —
        # by the time the consumer reads them they have usually landed
        for leaf in rows.values():
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        host = {k: np.asarray(v) for k, v in rows.items()}
        self.stats.note_harvest(host["tokens"], host["mask"])
        t_harvest = time.perf_counter()
        completed = []
        for j, slot in enumerate(finished):  # slot order: deterministic
            req = self._slots[slot]
            self._slots[slot] = None
            self._seeded[slot] = False
            self._trace_request(
                req, slot, t_harvest, gen_len=float(host["mask"][j].sum())
            )
            if self.spec is not None:
                # free the row's block refs; blocks the prefix cache (or a
                # sharing sibling) still holds stay allocated. The device
                # table row goes stale, which is harmless: the slot is
                # frozen done and every stale position is slot-masked out
                # of (row-independent) attention until the next refill
                # overwrites the row.
                self.allocator.release(self._row_blocks[slot])
                self._row_blocks[slot] = None
                self._alloc_upto[slot] = 0
                self._steps_bound[slot] = 0
            completed.append(
                CompletedSequence(
                    index=req.index,
                    prompt_ids=req.input_ids,
                    prompt_mask=req.attention_mask,
                    tokens=host["tokens"][j],
                    logprobs=host["logprobs"][j],
                    values=host["values"][j],
                    mask=host["mask"][j],
                    meta=req.meta,
                    t_enqueue=req.t_enqueue,
                    t_prefill0=req.t_refill0,
                    t_prefill1=req.t_refill1,
                    t_harvest=t_harvest,
                )
            )
        self.stats.harvested += len(completed)
        return completed

    def progress_snapshot(self) -> List[tuple]:
        """Per-slot decode progress for token streaming (paged backend):
        ``(index, meta, tokens)`` for every seeded live slot, where
        ``tokens`` is the host copy of the row's committed response so far
        (``_steps_bound`` is exact for live rows — non-spec rows advance
        in lockstep, spec rows read the device step counter; a row that
        finished mid-segment was harvested by the same :meth:`step`, so it
        never appears here with trailing post-eos positions). The serve
        pump diffs consecutive snapshots into stream deltas; their
        concatenation plus the harvest tail is exactly the masked response
        (pinned by ``tests/test_serve.py`` streaming parity)."""
        if self.spec is None:
            return []
        out: List[tuple] = []
        toks = None
        for slot in range(self.B):
            req = self._slots[slot]
            if req is None or not self._seeded[slot]:
                continue
            n = min(self._steps_bound[slot], self.N)
            if n <= 0:
                continue
            if toks is None:
                toks = np.asarray(self.state.tokens)  # one device fetch
            out.append((req.index, req.meta, toks[slot, :n].copy()))
        return out

    def _trace_request(
        self, req: "_Request", slot: int, t_harvest: float, gen_len: float = 0.0
    ) -> None:
        """Emit the request's lifecycle spans (queue wait → prefill →
        decode, closed by harvest) on this slot's track — a slot holds one
        request at a time, so per-slot tracks never overlap and a stalled
        generation is attributable to its exact row in the merged trace."""
        if self._tracer is None or req.t_refill1 <= 0.0:
            return
        track = f"engine/slot{slot}"
        self._tracer.add_complete_event(
            "engine/queue_wait", req.t_enqueue, req.t_refill0,
            track=track, index=req.index,
        )
        self._tracer.add_complete_event(
            "engine/prefill", req.t_refill0, req.t_refill1,
            track=track, index=req.index,
        )
        self._tracer.add_complete_event(
            "engine/decode", req.t_refill1, t_harvest,
            track=track, index=req.index,
        )
        if self._gamma:
            # the request's decode window IS draft-propose/verify rounds:
            # one span per request, so a low-acceptance straggler is
            # attributable to its exact row in the merged trace
            self._tracer.add_complete_event(
                "engine/spec_verify", req.t_refill1, t_harvest,
                track=track, index=req.index,
                gamma=self._gamma, tokens=gen_len,
            )

    def step(self) -> List[CompletedSequence]:
        """One admit → prefill-span → segment → harvest turn; returns newly
        completed sequences (possibly empty while long rows keep decoding).
        With ``prefill_chunk`` set, the prefill work this step runs is at
        most one chunk per still-prefilling slot, so live decode slots are
        never stalled longer than one chunk's prefill before their next
        segment (the decode-stall gauges measure exactly this)."""
        self._admit()
        if self.spec is not None:
            self._advance_prefill()
        if self._decoding() == 0:
            return []
        if self.spec is not None:
            # reserve writable blocks for the columns this segment may
            # produce, then push the grown tables to device
            if self._ensure_decode_blocks(self.fns.segment_len):
                self._push_tables()
            self._note_block_usage()
        if self._span is not None:
            with self._span(
                "rollout/segment", live=self.live, pending=self.pending
            ) as sp:
                self.state, live_steps, steps = self.fns.decode_segment(
                    self.params, self.state
                )
                sp.fence((self.state.done, self.state.tokens))
            self.stats.decode_s += sp.duration
        else:
            t0 = time.perf_counter()
            self.state, live_steps, steps = self.fns.decode_segment(
                self.params, self.state
            )
            # fetching the step counters below blocks on the segment anyway
        steps = int(np.asarray(steps))
        live_steps = int(np.asarray(live_steps))
        if self._span is None:
            self.stats.decode_s += time.perf_counter() - t0
        self.stats.segments += 1
        self.stats.decode_steps += steps
        self.stats.slot_steps += steps * self.B
        self.stats.live_slot_steps += live_steps
        if self._gamma:
            cur = self._read_spec_counters()
            self.stats.spec_rounds = cur["rounds"] - self._spec_base["rounds"]
            self.stats.spec_accepted = (
                cur["accepted"] - self._spec_base["accepted"]
            )
            self.stats.spec_live_rounds = (
                cur["live_rounds"] - self._spec_base["live_rounds"]
            )
            self.stats.spec_committed = (
                cur["committed"] - self._spec_base["committed"]
            )
        if self.spec is not None:
            step_np = np.asarray(self.state.step) if self._gamma else None
            for slot in range(self.B):
                if self._slots[slot] is not None and self._seeded[slot]:
                    if self._gamma:
                        # per-row accepted-length divergence: under
                        # speculation rows advance different amounts per
                        # round, and the device step counter IS each row's
                        # true committed length
                        self._steps_bound[slot] = int(step_np[slot])
                    else:
                        self._steps_bound[slot] = min(
                            self.N, self._steps_bound[slot] + steps
                        )
        return self._harvest()
