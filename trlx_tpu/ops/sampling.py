"""Jitted autoregressive generation with an explicit KV cache.

The reference's dominant hot loop is HF ``generate`` (SURVEY.md §3.2); here it
is one compiled program: a prefill forward that fills the cache for the
(left-padded) prompt block, then a ``lax.while_loop`` decode with per-sample
eos early-exit — static shapes, no host round-trips.

The ``adjust_logits`` hook lets algorithms reshape sampling logits on device —
ILQL's ``logπ + β(minQ − V)`` advantage reshaping plugs in here (reference:
``trlx/models/modeling_ilql.py:280-317``).
"""

import dataclasses
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Sampling settings (HF-compatible field names, reference
    ``method.gen_kwargs``)."""

    max_new_tokens: int = 40
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    do_sample: bool = True
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    min_new_tokens: int = 0
    # Per-row RNG streams: row i samples from its own key chain
    # ``fold_in(rng, i)`` split once per decode step, so a sequence's sampled
    # tokens depend only on (its key, its step) — never on batch composition
    # or slot position. Required by (and implied by) continuous-batching
    # rollouts, where a sequence migrates through refilled cache slots; the
    # default batch-wide stream is kept for byte-for-byte compatibility of
    # existing runs.
    per_row_rng: bool = False

    @staticmethod
    def from_gen_kwargs(kwargs: Dict[str, Any], eos_token_id=None, pad_token_id=0) -> "GenerationConfig":
        known = {f.name for f in dataclasses.fields(GenerationConfig)}
        clean = {k: v for k, v in kwargs.items() if k in known}
        clean.setdefault("eos_token_id", eos_token_id)
        clean.setdefault("pad_token_id", pad_token_id)
        # ILQL passes beta/temperature through gen_kwargs; beta is handled by
        # the adjust_logits hook, so it is not a GenerationConfig field.
        return GenerationConfig(**clean)


def apply_transition_mask(
    mask: jax.Array,  # [Vm, Vm'] bool: allowed next-token per last-token
    last_tokens: jax.Array,  # [B] or [B, T] the conditioning token(s)
    logits: jax.Array,  # [..., V] matching last_tokens' leading dims
) -> jax.Array:
    """Disallow transitions: ``mask[last, next] == False`` → −inf-ish logits.

    Masks smaller than the vocab disallow out-of-range *next* tokens;
    out-of-range *last* tokens (no transition row exists) sample
    unconstrained rather than borrowing an unrelated row's constraints.
    Shared by the step sampler's logit-mask hook and the speculative
    decoder (both must agree exactly for lossless verification).
    """
    last = jnp.clip(last_tokens, 0, mask.shape[0] - 1)
    sel = mask[last]  # [..., mask_vocab]
    V = logits.shape[-1]
    if mask.shape[1] >= V:  # mask over a padded/larger vocab: truncate
        allowed = sel[..., :V]
    else:  # mask narrower than vocab: out-of-range tokens disallowed
        allowed = jnp.zeros(logits.shape, bool)
        allowed = allowed.at[..., : mask.shape[1]].set(sel)
    row_known = (last_tokens >= 0) & (last_tokens < mask.shape[0])
    allowed = allowed | ~row_known[..., None]
    return jnp.where(allowed, logits, -1e10)


def process_logits(
    logits: jax.Array,  # [B, V]
    temperature: float,
    top_k: int,
    top_p: float,
) -> jax.Array:
    """Standard temperature / top-k / top-p filtering (returns logits)."""
    if temperature != 1.0:
        logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumprobs = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep top-1)
        keep_sorted = cumprobs - probs < top_p
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return logits




def per_row_keys(rng: jax.Array, batch_size: int) -> jax.Array:
    """Derive ``[B, 2]`` independent per-row key chains from one key.

    Row ``i``'s chain starts at ``fold_in(rng, i)``; every decode step splits
    it once (``split_row_keys``). The single source of truth for BOTH the
    plain sampler's ``per_row_rng`` mode and the continuous-batching engine —
    they must agree exactly for the slot-refill bit-parity guarantee."""
    return jax.vmap(lambda i: jax.random.fold_in(rng, i))(
        jnp.arange(batch_size, dtype=jnp.int32)
    )


def split_row_keys(keys: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One decode step of every row's chain: ``[B, 2]`` keys → (next chain
    keys, this step's sample keys), both ``[B, 2]``."""
    pairs = jax.vmap(lambda k: jax.random.split(k))(keys)  # [B, 2, 2]
    return pairs[:, 0], pairs[:, 1]


def sample_token_from_logits(
    logits: jax.Array,  # [B, V] raw last-position logits
    step_out: Dict[str, Any],
    sample_rng: jax.Array,  # [2] batch-wide key, or [B, 2] per-row keys
    config: GenerationConfig,
    step: jax.Array,  # scalar, or [B] per-slot decode steps
    adjust_logits: Optional[Callable[[Dict[str, Any], jax.Array], jax.Array]],
) -> Tuple[jax.Array, jax.Array]:
    """Shared sampling semantics for every decode loop: adjust-logits hook,
    min_new_tokens eos blocking, temperature/top-k/top-p filtering,
    sample-or-argmax, and behavior logprob of the chosen token.

    ``sample_rng`` may be one batch-wide key (historical behavior) or a
    ``[B, 2]`` stack of per-row keys; ``step`` may be a scalar (all rows in
    lockstep) or a ``[B]`` vector (continuous batching: slots at different
    depths). Per-row sampling is a vmapped categorical, so row ``i``'s token
    depends only on its own key and logits."""
    if adjust_logits is not None:
        logits = adjust_logits(step_out, logits)
    logits = logits.astype(jnp.float32)
    if config.eos_token_id is not None and config.min_new_tokens > 0:
        block_eos = jnp.asarray(step < config.min_new_tokens)
        if block_eos.ndim:  # [B] per-slot steps → broadcast over the vocab
            block_eos = block_eos[:, None]
        logits = jnp.where(
            block_eos
            & (jnp.arange(logits.shape[-1])[None, :] == config.eos_token_id),
            -jnp.inf,
            logits,
        )
    filtered = process_logits(logits, config.temperature, config.top_k, config.top_p)
    if config.do_sample:
        if sample_rng.ndim == 2:  # per-row key chains
            next_token = jax.vmap(
                lambda k, row: jax.random.categorical(k, row)
            )(sample_rng, filtered)
        else:
            next_token = jax.random.categorical(sample_rng, filtered, axis=-1)
    else:
        next_token = jnp.argmax(filtered, axis=-1)
    logprob = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), next_token[:, None], axis=-1
    )[:, 0]
    return next_token, logprob


_NON_CARRY_KEYS = (
    "cache", "logits", "branch_input", "pre_norm_hidden", "encoder_hidden",
    "router_aux_loss",  # scalar vector, not [B, ...] — and unused in decode
)


def last_step_info(out: Dict[str, Any]) -> Dict[str, Any]:
    """Keep only last-position views of model outputs so the while_loop
    carry has step-invariant shapes (prefill is [B,P,…], decode [B,1,…])."""
    info = {}
    for k, v in out.items():
        if k in _NON_CARRY_KEYS or v is None:
            continue
        info[k] = jax.tree_util.tree_map(lambda x: x[:, -1], v)
    return info


class GenerationOutput(NamedTuple):
    sequences: jax.Array  # [B, P + N] prompt (left-padded) ‖ response
    response_tokens: jax.Array  # [B, N] pad-filled after eos
    response_mask: jax.Array  # [B, N] 1 on real response tokens (incl. eos)
    response_logprobs: jax.Array  # [B, N] behavior logprobs of sampled tokens
    response_values: jax.Array  # [B, N] value-head outputs (0 if no head)
    prompt_mask: jax.Array  # [B, P]


def generate(
    apply_fn: Callable[..., Dict[str, Any]],
    params: Any,
    init_cache_fn: Callable[[int, int], Any],
    input_ids: jax.Array,  # [B, P] left-padded prompts
    attention_mask: jax.Array,  # [B, P]
    rng: jax.Array,
    config: GenerationConfig,
    adjust_logits: Optional[Callable[[Dict[str, Any], jax.Array], jax.Array]] = None,
) -> GenerationOutput:
    """Sample ``max_new_tokens`` continuations for a batch of prompts.

    ``apply_fn(params, input_ids, attention_mask, positions, cache,
    cache_index)`` must return a dict with at least ``logits`` and ``cache``
    (the model wrappers' ``__call__``). ``adjust_logits(step_outputs, logits)``
    may reshape the last-token logits before sampling (ILQL).

    Fully jittable; wrap in ``jax.jit``/``pjit`` with static ``config``.
    """
    B, P = input_ids.shape
    N = config.max_new_tokens
    S = P + N
    input_ids = input_ids.astype(jnp.int32)

    cache = init_cache_fn(B, S)
    # slot mask over the full cache: prompt mask then zeros (filled as we go)
    slot_mask = jnp.concatenate(
        [attention_mask.astype(jnp.int32), jnp.zeros((B, N), jnp.int32)], axis=1
    )

    # ---- prefill ----
    # only the last position's logits seed the sampler: restrict the vocab
    # projection to it (the full-span projection is the prefill's biggest op)
    prefill_out = apply_fn(
        params,
        input_ids,
        attention_mask=slot_mask,
        positions=None,
        cache=cache,
        cache_index=jnp.asarray(0, jnp.int32),
        logits_span=(P - 1, P),
    )
    cache = prefill_out["cache"]
    last_logits = prefill_out["logits"][:, -1, :]  # [B, V]
    prompt_len = jnp.sum(attention_mask, axis=1).astype(jnp.int32)  # [B]

    class Carry(NamedTuple):
        tokens: jax.Array  # [B, N]
        logprobs: jax.Array  # [B, N]
        values: jax.Array  # [B, N]
        mask: jax.Array  # [B, N]
        slot_mask: jax.Array  # [B, S]
        cache: Any
        logits: jax.Array  # [B, V] logits for the next sample
        step_out: Any  # last-position views of last forward (for adjust_logits)
        done: jax.Array  # [B]
        step: jax.Array  # scalar
        rng: jax.Array

    def sample_step(carry: Carry) -> Carry:
        if config.per_row_rng:
            rng, sample_rng = split_row_keys(carry.rng)
        else:
            rng, sample_rng = jax.random.split(carry.rng)
        next_token, logprob = sample_token_from_logits(
            carry.logits, carry.step_out, sample_rng, config, carry.step, adjust_logits
        )

        next_token = jnp.where(carry.done, config.pad_token_id, next_token).astype(jnp.int32)
        live = ~carry.done
        tokens = carry.tokens.at[:, carry.step].set(next_token)
        logprobs = carry.logprobs.at[:, carry.step].set(jnp.where(live, logprob, 0.0))
        values = carry.values.at[:, carry.step].set(
            jnp.where(live, carry_step_value(carry), 0.0)
        )
        mask = carry.mask.at[:, carry.step].set(live.astype(jnp.int32))

        done = carry.done
        if config.eos_token_id is not None:
            done = done | (next_token == config.eos_token_id)

        # write slot mask for this token (live samples only)
        slot = P + carry.step
        slot_mask = carry.slot_mask.at[:, slot].set(live.astype(jnp.int32))

        # forward one step
        out = apply_fn(
            params,
            next_token[:, None],
            attention_mask=slot_mask,
            positions=(prompt_len + carry.step)[:, None],
            cache=carry.cache,
            cache_index=slot,
        )
        return Carry(
            tokens=tokens,
            logprobs=logprobs,
            values=values,
            mask=mask,
            slot_mask=slot_mask,
            cache=out["cache"],
            logits=out["logits"][:, -1, :],
            step_out={**last_step_info(out), "last_tokens": next_token},
            done=done,
            step=carry.step + 1,
            rng=rng,
        )

    def carry_step_value(carry: Carry) -> jax.Array:
        # value prediction for the *state before* sampling this token
        if "value" in carry.step_out:
            return carry.step_out["value"]
        return jnp.zeros((B,), jnp.float32)

    def cond(carry: Carry) -> jax.Array:
        return (carry.step < N) & ~jnp.all(carry.done)

    init = Carry(
        tokens=jnp.full((B, N), config.pad_token_id, jnp.int32),
        logprobs=jnp.zeros((B, N), jnp.float32),
        values=jnp.zeros((B, N), jnp.float32),
        mask=jnp.zeros((B, N), jnp.int32),
        slot_mask=slot_mask,
        cache=cache,
        logits=last_logits,
        step_out={**last_step_info(prefill_out), "last_tokens": input_ids[:, -1]},
        done=jnp.zeros((B,), bool),
        step=jnp.asarray(0, jnp.int32),
        rng=per_row_keys(rng, B) if config.per_row_rng else rng,
    )
    final = jax.lax.while_loop(cond, sample_step, init)

    sequences = jnp.concatenate([input_ids, final.tokens], axis=1)
    return GenerationOutput(
        sequences=sequences,
        response_tokens=final.tokens,
        response_mask=final.mask,
        response_logprobs=final.logprobs,
        response_values=final.values,
        prompt_mask=attention_mask.astype(jnp.int32),
    )


def generate_seq2seq(
    encode_fn: Callable[..., Tuple[jax.Array, Any]],
    decode_fn: Callable[..., Dict[str, Any]],
    params: Any,
    input_ids: jax.Array,  # [B, P] right-padded encoder prompts
    attention_mask: jax.Array,  # [B, P]
    rng: jax.Array,
    config: GenerationConfig,
    start_token_id: int = 0,
    adjust_logits: Optional[Callable[[Dict[str, Any], jax.Array], jax.Array]] = None,
) -> GenerationOutput:
    """Seq2seq sampling: one encoder pass, then a ``lax.while_loop`` decoder
    (reference: HF ``generate`` on the T5 wrappers, used by the seq2seq PPO/
    ILQL paths ``trlx/trainer/accelerate_ppo_trainer.py:152-179``,
    ``modeling_ilql.py:460-488``).

    ``encode_fn(params, input_ids, attention_mask, max_decode_len)`` returns
    ``(encoder_hidden, decoder_cache)`` with cross-attn K/V prefilled;
    ``decode_fn(params, decoder_input_ids, encoder_hidden, encoder_mask,
    cache, cache_index)`` returns at least ``logits`` and ``cache``.

    Decoder sequences all start at slot 0 with ``start_token_id`` — no
    left-padding complications. Fully jittable with static ``config``.
    """
    B, P = input_ids.shape
    N = config.max_new_tokens
    input_ids = input_ids.astype(jnp.int32)

    enc_hidden, cache = encode_fn(params, input_ids, attention_mask, N + 1)
    start = jnp.full((B, 1), start_token_id, jnp.int32)
    out0 = decode_fn(
        params, start, enc_hidden, attention_mask, cache, jnp.asarray(0, jnp.int32)
    )

    class Carry(NamedTuple):
        tokens: jax.Array
        logprobs: jax.Array
        values: jax.Array
        mask: jax.Array
        cache: Any
        logits: jax.Array
        step_out: Any
        done: jax.Array
        step: jax.Array
        rng: jax.Array

    def sample_step(carry: Carry) -> Carry:
        if config.per_row_rng:
            rng, sample_rng = split_row_keys(carry.rng)
        else:
            rng, sample_rng = jax.random.split(carry.rng)
        next_token, logprob = sample_token_from_logits(
            carry.logits, carry.step_out, sample_rng, config, carry.step, adjust_logits
        )

        next_token = jnp.where(carry.done, config.pad_token_id, next_token).astype(jnp.int32)
        live = ~carry.done
        tokens = carry.tokens.at[:, carry.step].set(next_token)
        logprobs = carry.logprobs.at[:, carry.step].set(jnp.where(live, logprob, 0.0))
        value = carry.step_out.get("value", jnp.zeros((B,), jnp.float32))
        values = carry.values.at[:, carry.step].set(jnp.where(live, value, 0.0))
        mask = carry.mask.at[:, carry.step].set(live.astype(jnp.int32))

        done = carry.done
        if config.eos_token_id is not None:
            done = done | (next_token == config.eos_token_id)

        out = decode_fn(
            params, next_token[:, None], enc_hidden, attention_mask,
            carry.cache, carry.step + 1,
        )
        return Carry(
            tokens=tokens,
            logprobs=logprobs,
            values=values,
            mask=mask,
            cache=out["cache"],
            logits=out["logits"][:, -1, :],
            step_out={**last_step_info(out), "last_tokens": next_token},
            done=done,
            step=carry.step + 1,
            rng=rng,
        )

    def cond(carry: Carry) -> jax.Array:
        return (carry.step < N) & ~jnp.all(carry.done)

    init = Carry(
        tokens=jnp.full((B, N), config.pad_token_id, jnp.int32),
        logprobs=jnp.zeros((B, N), jnp.float32),
        values=jnp.zeros((B, N), jnp.float32),
        mask=jnp.zeros((B, N), jnp.int32),
        cache=out0["cache"],
        logits=out0["logits"][:, -1, :],
        step_out={**last_step_info(out0), "last_tokens": start[:, 0]},
        done=jnp.zeros((B,), bool),
        step=jnp.asarray(0, jnp.int32),
        rng=per_row_keys(rng, B) if config.per_row_rng else rng,
    )
    final = jax.lax.while_loop(cond, sample_step, init)

    sequences = jnp.concatenate([input_ids, final.tokens], axis=1)
    return GenerationOutput(
        sequences=sequences,
        response_tokens=final.tokens,
        response_mask=final.mask,
        response_logprobs=final.logprobs,
        response_values=final.values,
        prompt_mask=attention_mask.astype(jnp.int32),
    )
