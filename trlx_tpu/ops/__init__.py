"""Compute ops: jitted generation, sampling transforms, attention kernels."""
