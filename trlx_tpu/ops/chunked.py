"""Stream hidden states through the vocab projection in T-chunks.

The ``[B, T, V]`` logits tensor is the peak-memory item of large-vocab
training forwards (BLOOM's V = 250k). Losses that reduce over tokens
(SFT cross-entropy, DPO completion logprobs) never need the whole tensor at
once: this helper reshapes ``[B, T, ...]`` rows into chunks, projects each
chunk via the model's ``project_logits``, and folds a caller-supplied
reduction under ``jax.checkpoint`` — forward AND backward peak at
``[B, chunk, V]``. One definition of the pad/reshape/scan machinery so the
call sites (``models/sft.py::SFTConfig.chunked_loss``,
``trainer/dpo.py::_completion_logps``) cannot drift apart.
"""

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp


def stream_projected_reduce(
    module,
    params,
    hidden: jax.Array,  # [B, T, E]
    arrays: Sequence[Tuple[jax.Array, Any]],  # ([B, T] array, pad_value) ...
    chunk: int,
    init: Any,  # reduction carry init
    body_fn: Callable[..., Any],  # (carry, logits, *chunk_arrays) -> carry
) -> Any:
    """Fold ``body_fn`` over T-chunks of projected logits.

    ``arrays`` ride along chunk-aligned (padded with their declared pad
    value, e.g. ``IGNORE_INDEX`` labels or a zero mask, so padding
    contributes nothing to a well-formed reduction). The chunk size is
    honored for ANY T via padding — T is frequently odd/prime after the
    causal shift, and a divisor fallback would quietly degrade to
    token-at-a-time.
    """
    B, T, E = hidden.shape
    C = min(chunk, T)
    pad = (-T) % C
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        arrays = [
            (jnp.pad(a, ((0, 0), (0, pad)), constant_values=v), v)
            for a, v in arrays
        ]
    n_chunks = (T + pad) // C
    hc = hidden.reshape(B, n_chunks, C, E).transpose(1, 0, 2, 3)
    acs = [a.reshape(B, n_chunks, C).transpose(1, 0, 2) for a, _ in arrays]

    def body(carry, xs):
        h, *rest = xs
        logits = module.apply(
            {"params": params}, h, method=type(module).project_logits
        )
        return body_fn(carry, logits, *rest), None

    carry, _ = jax.lax.scan(jax.checkpoint(body), init, (hc, *acs))
    return carry
