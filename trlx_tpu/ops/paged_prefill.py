"""Pallas paged-*prefill* attention: prompt flash attention computed in KV
chunks, reading and writing K/V through the block table — no dense view.

PR 12 (``ops/paged_attention.py``) deleted the per-segment gather/scatter
tax from paged *decode*; every refill prefill, however, still ran
gather → dense prefill → scatter (``ops/slot_refill.py::_make_refill``) —
the last dense-view copy on the generation hot path. This kernel closes it:
the refill forward's attention reads committed prefix blocks and the
chunk's own freshly-written K/V straight from the pool (each of a row's
blocks fetched into VMEM exactly once, driven by the scalar-prefetched
block table), and the chunk's K/V is committed by the caller
(``models/transformer.py::Attention``) with drop-mode writes through the
table — no dense-view gather on entry, no scatter on exit.

Bit-parity is the contract, inherited verbatim from the decode kernel's
design rules (pinned by ``tests/test_paged_attention.py``):

1. The kernel replicates the dense einsum path's exact op sequence on the
   per-row slice: grid steps only *land* KV blocks in VMEM scratch, then
   one compute step runs ``q·k / sqrt(depth) + bias``, ``jax.nn.softmax``
   (f32) and ``p·v`` over the full ``[T, S]`` score block — the same ops
   on the same shapes the dense path runs per row. Batch-dim slicing is
   the established bit-safe decomposition; splitting the score einsum per
   KV block is NOT (degenerate dots lower differently — see the decode
   kernel's notes), so all compute waits for the assembled row.
2. Masked key slots carry the dense path's additive ``-1e9`` bias and
   underflow softmax to exactly ``0.0`` — recycled-block stale values and
   not-yet-written pool positions contribute nothing, the same convention
   every kernel in this repo pins (``ops/pallas_utils.py``).

Chunked prefill (``ops/slot_refill.py`` chunk programs,
``engine.prefill_chunk``) calls this kernel with ``T = chunk`` queries
over the FULL ``S``-wide key row, with columns ``>= end`` bias-masked: a
chunk's queries see only the committed columns ``[0, end)`` (masked
columns contribute exact zeros), while the key width — and hence the
score dots' shapes — stays identical to the monolithic pass's, so
chunked output is bit-identical to unchunked (pinned across chunk sizes
by the parity suite; truncating the key axis instead changes the dot's
lowering at some shapes — 1-ulp contraction drift).

Off-TPU the kernel runs under the Pallas interpreter (the body as ordinary
XLA ops — what the CPU tier-1 parity suite pins); builds without the
Mosaic backend fall back to :func:`paged_prefill_attention_reference` with
identical semantics.

Hardware notes (``/opt/skills/guides/pallas_guide.md``): block fetches are
``(block_size, KV, D)`` tiles pipelined by the grid; keep
``engine.kv_block_size`` a multiple of 8 (f32 sublane) and ``D`` a
multiple of 128 on real TPUs. VMEM holds the assembled row
(``TB·block_size × KV × D``) plus the ``[T, S]`` f32 score block — bound
``T`` with ``engine.prefill_chunk`` for long prompts on chip.

Registered in ``analysis/kernels.py::KERNEL_PARITY`` as ``paged-prefill``
(the verify seam rides the same body as ``paged-verify``): graftlint's
kernel-discipline pass enforces the gate/purity/parity conventions
statically (docs/STATIC_ANALYSIS.md).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from trlx_tpu.ops.pallas_utils import (
    align_rows,
    clamp_block_table,
    has_pallas_tpu,
    pad_bias_to,
    paged_pool_grid_spec,
    resolve_interpret,
)

__all__ = [
    "paged_prefill_attention",
    "paged_prefill_attention_reference",
]


def _paged_prefill_kernel(
    tbl_ref,  # scalar-prefetch (B, TB) int32 — drives the k/v index maps
    q_ref,  # (1, T, H, D) chunk queries (rotary already applied)
    bias_ref,  # (1, HB, T, Sp) f32 additive bias (slot-causal + validity
    #   [+alibi]); HB is 1 (head-uniform mask) or H (per-head ALiBi slopes)
    k_ref,  # (1, bs, KV, D) — pool block tbl[b, j], in place
    v_ref,  # (1, bs, KV, D)
    o_ref,  # (1, T, H, D)
    k_buf_ref,  # VMEM scratch (Sa, KV, D): the row's K, assembled per block
    v_buf_ref,  # VMEM scratch (Sa, KV, D)
    *,
    seq_len: int,  # S — logical key columns visible to this chunk
    block_size: int,
    num_blocks: int,  # TB
    group: int,  # query heads per kv head (GQA)
    head_dim: int,
):
    j = pl.program_id(1)
    # assembly steps: land this block's K/V in the row's VMEM buffers; all
    # compute waits for the full row (per-block score dots split the
    # einsum's free dim, which is not bit-preserving for tiny blocks —
    # same rule as the decode kernel)
    k_buf_ref[pl.ds(j * block_size, block_size), :, :] = k_ref[0]
    v_buf_ref[pl.ds(j * block_size, block_size), :, :] = v_ref[0]

    @pl.when(j == num_blocks - 1)
    def _finish():
        # the dense path on the per-row slice, op for op: GQA repeat;
        # scores = einsum(q, k) / sqrt(depth); scores += bias;
        # probs = softmax(f32(scores)).astype(dtype); out = einsum(probs, v)
        # The unit batch dim is KEPT on every operand so both dots carry
        # the dense path's exact dimension numbers ("bthd,bshd->bhts" /
        # "bhts,bshd->bthd", batch size 1 instead of B): batch-dim slicing
        # is the established bit-safe decomposition, while DROPPING the
        # batch dim changes the dot's structure — and for T > 1 matmuls
        # inside the interpreter's grid machinery that can change which
        # CPU emitter XLA picks, shifting contraction bits by 1 ulp. A
        # third lowering landmine for the next kernel author, beside the
        # two the decode kernel documents.
        q = q_ref[...]  # (1, T, H, D)
        k = k_buf_ref[0:seq_len, :, :][None]
        vv = v_buf_ref[0:seq_len, :, :][None]
        if group > 1:
            k = jnp.repeat(k, group, axis=2)
            vv = jnp.repeat(vv, group, axis=2)
        raw = jnp.einsum("bthd,bshd->bhts", q, k)  # (1, H, T, S)
        depth = jnp.asarray(head_dim, raw.dtype)
        scores = raw / jnp.sqrt(depth)
        # (1, HB, T, S) broadcasts over heads exactly like the dense
        # path's [B, HB, T, S] bias against its [B, H, T, S] scores
        bias = bias_ref[...][:, :, :, 0:seq_len]
        scores = scores + bias.astype(scores.dtype)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
            raw.dtype
        )
        out = jnp.einsum("bhts,bshd->bthd", probs, vv)  # (1, T, H, D)
        o_ref[...] = out.astype(o_ref.dtype)


def paged_prefill_attention(
    q: jax.Array,  # (B, T, H, D) chunk queries (rotary already applied)
    k_pool: jax.Array,  # (NB, bs, KV, D) — the persistent block pool
    v_pool: jax.Array,  # (NB, bs, KV, D)
    block_table: jax.Array,  # (B, TB) int32; out-of-range ids clamp (their
    #   lanes are bias-masked or belong to padding rows whose output drops)
    bias: jax.Array,  # (B, HB, T, S) additive f32 bias (0 visible / -1e9
    #   masked [+ ALiBi]); HB is 1, or H for per-head slopes
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Chunked prompt attention reading K/V through the block table.

    Returns ``(B, T, H, D)`` in ``q.dtype`` — bit-identical to gathering
    the pool into a dense ``[B, S, KV, D]`` view and running the dense
    einsum attention with the same ``bias`` (pinned by the parity suite).
    The pool is only read; the chunk's own K/V must already be committed
    through the table (``models/transformer.py`` does the one drop-mode
    write per chunk position before calling in).
    """
    B, T, H, D = q.shape
    NB, bs, KV, _ = k_pool.shape
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    group = H // KV
    TB = block_table.shape[1]
    HB = bias.shape[1]
    if HB not in (1, H):
        raise ValueError(
            f"bias head dim {HB} must be 1 (head-uniform) or H={H}"
        )
    if bias.shape[2] != T:
        raise ValueError(
            f"bias query dim {bias.shape[2]} != chunk length T={T}"
        )
    S = bias.shape[3]
    if TB * bs < S:
        raise ValueError(
            f"block table covers {TB * bs} columns < bias width {S}"
        )
    if not has_pallas_tpu():  # pragma: no cover - exotic CPU-only builds
        return paged_prefill_attention_reference(
            q, k_pool, v_pool, block_table, bias
        )
    interpret = resolve_interpret(interpret)
    S_pad = TB * bs
    # scratch rounded up for hardware tiling; the kernel reads [0:S] slices
    S_align = align_rows(S_pad, interpret)
    bias_p = pad_bias_to(bias, S_pad)
    tbl = clamp_block_table(block_table, NB)

    kernel = functools.partial(
        _paged_prefill_kernel,
        seq_len=S,
        block_size=bs,
        num_blocks=TB,
        group=group,
        head_dim=D,
    )
    grid_spec = paged_pool_grid_spec(
        batch=B,
        table_blocks=TB,
        block_size=bs,
        kv_heads=KV,
        head_dim=D,
        q_block=(1, T, H, D),
        bias_block=(1, HB, T, S_pad),
        out_block=(1, T, H, D),
        scratch_rows=S_align,
        k_dtype=k_pool.dtype,
        v_dtype=v_pool.dtype,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, H, D), q.dtype),
        interpret=interpret,
    )(tbl, q, bias_p, k_pool, v_pool)


def paged_prefill_attention_reference(
    q: jax.Array,  # (B, T, H, D)
    k_pool: jax.Array,  # (NB, bs, KV, D)
    v_pool: jax.Array,  # (NB, bs, KV, D)
    block_table: jax.Array,  # (B, TB)
    bias: jax.Array,  # (B, HB, T, S); HB is 1 or H (per-head ALiBi)
) -> jax.Array:
    """Gather-then-dense oracle: the exact computation the gather refill's
    dense einsum attention performs on the gathered view (test reference,
    and the fallback when the Mosaic backend is unavailable)."""
    B, T, H, D = q.shape
    NB, bs, KV, _ = k_pool.shape
    S = bias.shape[3]

    def view(pool):
        v = pool[jnp.minimum(block_table, NB - 1)]  # (B, TB, bs, KV, D)
        v = v.reshape(B, -1, KV, D)[:, :S]
        if KV < H:
            v = jnp.repeat(v, H // KV, axis=2)
        return v

    k, v = view(k_pool), view(v_pool)
    depth = jnp.asarray(D, q.dtype)
    scores = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.sqrt(depth)
    scores = scores + bias.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)
