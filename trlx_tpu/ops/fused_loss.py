"""Fused Pallas learner-step kernel: GAE + whitening + clipped PPO loss.

The generation hot path went native in PRs 12/13/16; the learner hot path
stayed staged XLA: ``PPOConfig.get_advantages_and_returns`` (a reverse
``lax.scan``), ``utils/stats.py::whiten`` (two masked reduction passes),
and ``PPOConfig.loss`` (clipped pg/value terms plus a dozen masked stats)
each materialize and re-read the ``[B, R]`` response-window operands from
HBM. HEPPO-GAE (arxiv 2501.12703) makes the case that GAE is a
pipeline-friendly fusion target; this module fuses the whole chain into
ONE Pallas program: each ``[B, R]`` operand is loaded into VMEM exactly
once (its whole-operand BlockSpec is the one HBM→VMEM crossing), then the
kernel body runs the reversed GAE recurrence, the masked two-pass
mean/var whitening, and the clipped losses + clipfrac/approx-KL stats
(and the ``dist/*`` sketches, when enabled) straight-line on the resident
operands — no per-stage HBM round-trips (A/B:
``benchmarks/LOSS_KERNEL_cpu.json``).

Bit-parity is the contract, same as every kernel in this repo: the fused
program must equal the staged XLA path to the bit — loss, grads, every
stat, every sketch bin. The design rule that makes that cheap to
guarantee: the kernel body does not *reimplement* anything. It calls the
genuine ``PPOConfig.get_advantages_and_returns`` and ``PPOConfig.loss``
methods on the VMEM-resident slices (:func:`_loss_core`), so the op
sequence inside the kernel is the reference op sequence by construction —
the kernel only changes where the operands live. The backward pass is a
second Pallas program that re-assembles the operands and differentiates
the same ``_loss_core`` trace with ``jax.vjp`` (recompute-over-residuals,
the flash-attention precedent), wired through ``jax.custom_vjp``.
Gradients flow to ``logprobs`` and ``values`` only: the remaining
operands (``old_*``, ``rewards``, ``mask``, ``behavior_logprobs``) are
batch constants in the trainer — no parameter reaches them — and the
XLA path's ``stop_gradient`` on advantages (and on returns, see
``get_advantages_and_returns``) makes the GAE chain a constant w.r.t.
params there too, so declaring them non-differentiable here is exact,
not an approximation (pinned by the grad-parity sweep in
``tests/test_fused_loss.py``).

Operands enter the kernel in their ORIGINAL dtypes — the methods cast
internally (``loss`` casts logprobs/values/mask to f32 but binds
``old_values`` at its incoming precision into the clip arithmetic), and
pre-casting host-side would change those mixed-precision bits.

The kernel's grid is deliberately a SINGLE step, not a row-block
assembly loop, and that choice is the fourth documented lowering
landmine (joining the three in ``ops/paged_attention.py`` /
``ops/paged_prefill.py``): the fused chain's reductions are global over
``[B, R]`` — the GAE scan is sequential in R and the whitening moments
span the whole mask — so every row must be VMEM-resident before any
compute can start and a multi-step grid saves no VMEM; what it DOES do
is wrap the compute step in the interpreter's cond-in-grid-loop, where
XLA CPU emits some of the masked sums with a different accumulation
order than the straight-line reference program — 1-ulp drift in scalar
stats, and at some block widths the loss itself. Relatedly, parity must
be pinned jit-to-jit *with every operand passed as a runtime argument*
(how the trainer actually runs): an eager op-by-op reference drifts
1 ulp in the scalar stat epilogue (inside one compiled program XLA
contracts ``1 − n/size`` into a fused multiply-add it cannot form across
eager dispatches), and a reference that *closes over* a bf16
``old_values`` lets XLA constant-fold the ``old_values ± cliprange``
clip bounds at a different precision than the runtime bf16 arithmetic —
a 2⁻¹¹-scale shift in the value loss, far beyond reduction jitter. All
pinned by ``tests/test_fused_loss.py``.

Off-TPU the program runs under the Pallas interpreter (the kernel body
as ordinary XLA ops — what the CPU tier-1 parity suite pins); builds
without the Mosaic backend fall back to the staged XLA composition with
identical semantics.

Hardware notes (``/opt/skills/guides/pallas_guide.md``): the GAE
recurrence is a ``lax.scan`` and the sketches are scatter-adds — both
trace into the kernel body and run today under the interpreter (the
pinned tier-1 contract); Mosaic's ability to lower them on-chip is the
next-TPU-window A/B (``docs/PERFORMANCE.md`` "Fused learner kernels").
``block_rows`` sets the batch-axis pad granularity (keep it a multiple
of 8, the f32 sublane, on chip); the response width pads to the
128-lane multiple.

Registered in ``analysis/kernels.py::KERNEL_PARITY`` as ``fused-loss``:
graftlint's kernel-discipline pass keeps ``fused_ppo_loss`` gated through
``pallas_utils``, forbids literal ``train/loss_kernel_pallas`` stamps
(GL1002 — the twice-shipped fallback-gauge bug), and fails the tree if
the staged reference or ``tests/test_fused_loss.py`` disappears
(docs/STATIC_ANALYSIS.md).
"""

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from trlx_tpu.observability.dynamics import SKETCH_BINS
from trlx_tpu.ops.pallas_utils import (
    LANES,
    align_rows,
    has_pallas_tpu,
    resolve_interpret,
)

__all__ = [
    "LossParams",
    "loss_params_of",
    "fused_ppo_loss",
    "fused_ppo_loss_reference",
]


class LossParams(NamedTuple):
    """The hashable subset of ``PPOConfig`` the fused program closes over
    (``jax.custom_vjp`` nondiff args must hash; method objects don't)."""

    gamma: float
    lam: float
    cliprange: float
    cliprange_value: float
    vf_coef: float
    iw_correction: str
    iw_clip: float
    dist_sketches: bool


def loss_params_of(method) -> LossParams:
    """Extract :class:`LossParams` from a ``PPOConfig``-shaped method."""
    return LossParams(
        gamma=float(method.gamma),
        lam=float(method.lam),
        cliprange=float(method.cliprange),
        cliprange_value=float(method.cliprange_value),
        vf_coef=float(method.vf_coef),
        iw_correction=str(method.iw_correction),
        iw_clip=float(method.iw_clip),
        dist_sketches=bool(method.dist_sketches),
    )


@functools.lru_cache(maxsize=None)
def _method_of(p: LossParams):
    """A fresh ``PPOConfig`` carrying ``p`` — the kernel body calls the
    genuine method implementations, never a transcription of them."""
    from trlx_tpu.models.ppo import PPOConfig  # late: models import this module

    return PPOConfig(
        gamma=p.gamma,
        lam=p.lam,
        cliprange=p.cliprange,
        cliprange_value=p.cliprange_value,
        vf_coef=p.vf_coef,
        iw_correction=p.iw_correction,
        iw_clip=p.iw_clip,
        dist_sketches=p.dist_sketches,
    )


def _loss_core(p: LossParams, logprobs, values, old_logprobs, old_values,
               rewards, mask, behavior_logprobs=None):
    """The staged XLA chain, verbatim, on whatever arrays it is handed:
    GAE → whiten → clipped loss + stats. Called by the reference path on
    HBM arrays and by the kernel body on VMEM slices — one definition is
    the bit-parity argument."""
    m = _method_of(p)
    advantages, returns = m.get_advantages_and_returns(old_values, rewards, mask)
    return m.loss(
        logprobs=logprobs,
        values=values,
        old_logprobs=old_logprobs,
        old_values=old_values,
        advantages=advantages,
        returns=returns,
        mask=mask,
        behavior_logprobs=behavior_logprobs,
    )


@functools.lru_cache(maxsize=None)
def _stat_keys(p: LossParams, shapes_dtypes: tuple, use_iw: bool):
    """Discover the loss's stats-dict keys abstractly (``jax.eval_shape``
    — zero FLOPs) and split them into scalar vs histogram outputs. The
    kernel packs stats in this key order; the host wrapper unpacks in the
    same order."""
    sds = [jax.ShapeDtypeStruct(s, d) for (s, d) in shapes_dtypes]
    _, stats = jax.eval_shape(
        lambda *ops: _loss_core(p, *ops[:6], ops[6] if use_iw else None),
        *(sds[:7] if use_iw else sds[:6]),
    )
    scalar_keys = tuple(k for k, v in stats.items() if v.shape == ())
    hist_keys = tuple(k for k, v in stats.items() if v.shape == (SKETCH_BINS,))
    leftover = set(stats) - set(scalar_keys) - set(hist_keys)
    if leftover:  # a new stats shape needs an output-packing decision here
        raise ValueError(f"unpackable loss stats shapes: {sorted(leftover)}")
    return scalar_keys, hist_keys


def _fused_loss_fwd_kernel(*refs, p, B, R, n_ops, scalar_keys, hist_keys):
    # single-step grid: every [B, R] operand block is already VMEM-resident
    # (loaded from HBM exactly once by its BlockSpec — the entire point;
    # the staged path re-reads them per stage), and the whole fused chain
    # runs straight-line on the slices. See the module docstring's fourth
    # landmine for why there is deliberately NO row-block assembly loop
    # here: the chain's reductions are global over [B, R] (GAE is
    # sequential in R, the whitening moments span the whole mask), so
    # row-blocking saves no VMEM — and a multi-step grid wraps the compute
    # in the interpreter's cond-in-loop, where XLA CPU emits some masked
    # sums with a different accumulation order (1-ulp drift).
    in_refs = refs[:n_ops]
    loss_ref, sc_ref, hist_ref = refs[n_ops:]
    ops = [ref[0:B, 0:R] for ref in in_refs]
    blp = ops[6] if n_ops == 7 else None
    loss, stats = _loss_core(p, *ops[:6], blp)
    loss_ref[...] = jnp.broadcast_to(loss.astype(jnp.float32), loss_ref.shape)
    sc = jnp.stack([stats[k].astype(jnp.float32) for k in scalar_keys])
    sc_ref[...] = jnp.broadcast_to(sc[:, None], sc_ref.shape)
    if hist_keys:
        hist_ref[...] = jnp.stack(
            [stats[k].astype(jnp.float32) for k in hist_keys]
        )
    else:
        hist_ref[...] = jnp.zeros(hist_ref.shape, jnp.float32)


def _fused_loss_bwd_kernel(*refs, p, B, R, n_ops):
    in_refs = refs[:n_ops]
    g_ref = refs[n_ops]
    dlp_ref, dv_ref = refs[n_ops + 1:]
    ops = [ref[0:B, 0:R] for ref in in_refs]
    blp = ops[6] if n_ops == 7 else None

    def loss_of(lp_s, v_s):
        loss, _ = _loss_core(p, lp_s, v_s, *ops[2:6], blp)
        return loss

    # recompute-over-residuals (the flash-bwd precedent): differentiate
    # the SAME _loss_core trace the forward ran, w.r.t. the two operands
    # gradients actually reach
    _, vjp = jax.vjp(loss_of, ops[0], ops[1])
    dlp, dv = vjp(g_ref[0, 0])
    # zero-fill then sub-slice store (NOT ``.at[...].set`` — a
    # full-coverage indexed update lowers to a scatter whose empty index
    # arrays Pallas rejects as captured constants)
    dlp_ref[...] = jnp.zeros(dlp_ref.shape, dlp_ref.dtype)
    dv_ref[...] = jnp.zeros(dv_ref.shape, dv_ref.dtype)
    dlp_ref[0:B, 0:R] = dlp.astype(dlp_ref.dtype)
    dv_ref[0:B, 0:R] = dv.astype(dv_ref.dtype)


def _shapes_dtypes(operands) -> tuple:
    return tuple((x.shape, jnp.dtype(x.dtype).name) for x in operands)


def _pad_operands(operands, B_pad, R_pad):
    B, R = operands[0].shape
    return [jnp.pad(x, ((0, B_pad - B), (0, R_pad - R))) for x in operands]


def _fwd_call(p, interpret, block_rows, operands):
    B, R = operands[0].shape
    B_pad = -(-B // block_rows) * block_rows
    R_pad = align_rows(R, interpret)
    n_ops = len(operands)
    scalar_keys, hist_keys = _stat_keys(
        p, _shapes_dtypes(operands), n_ops == 7
    )
    NS, NH = len(scalar_keys), max(1, len(hist_keys))
    kernel = functools.partial(
        _fused_loss_fwd_kernel,
        p=p,
        B=B,
        R=R,
        n_ops=n_ops,
        scalar_keys=scalar_keys,
        hist_keys=hist_keys,
    )
    op_spec = pl.BlockSpec((B_pad, R_pad), lambda: (0, 0))
    out_loss, out_sc, out_h = pl.pallas_call(
        kernel,
        grid=(),
        in_specs=[op_spec] * n_ops,
        out_specs=[
            pl.BlockSpec((1, LANES), lambda: (0, 0)),
            pl.BlockSpec((NS, LANES), lambda: (0, 0)),
            pl.BlockSpec((NH, SKETCH_BINS), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, LANES), jnp.float32),
            jax.ShapeDtypeStruct((NS, LANES), jnp.float32),
            jax.ShapeDtypeStruct((NH, SKETCH_BINS), jnp.float32),
        ],
        interpret=interpret,
    )(*_pad_operands(operands, B_pad, R_pad))
    return out_loss[0, 0], out_sc[:, 0], out_h


def _bwd_call(p, interpret, block_rows, operands, g_loss):
    B, R = operands[0].shape
    B_pad = -(-B // block_rows) * block_rows
    R_pad = align_rows(R, interpret)
    n_ops = len(operands)
    kernel = functools.partial(
        _fused_loss_bwd_kernel,
        p=p,
        B=B,
        R=R,
        n_ops=n_ops,
    )
    op_spec = pl.BlockSpec((B_pad, R_pad), lambda: (0, 0))
    g = jnp.broadcast_to(
        g_loss.astype(jnp.float32).reshape(1, 1), (1, LANES)
    )
    dlp, dv = pl.pallas_call(
        kernel,
        grid=(),
        in_specs=[op_spec] * n_ops + [pl.BlockSpec((1, LANES), lambda: (0, 0))],
        out_specs=[
            pl.BlockSpec((B_pad, R_pad), lambda: (0, 0)),
            pl.BlockSpec((B_pad, R_pad), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B_pad, R_pad), jnp.float32),
            jax.ShapeDtypeStruct((B_pad, R_pad), jnp.float32),
        ],
        interpret=interpret,
    )(*_pad_operands(operands, B_pad, R_pad), g)
    return dlp[0:B, 0:R], dv[0:B, 0:R]


# --- custom_vjp pairs (fixed arity: custom_vjp has no varargs, so the
# iw-corrected seven-operand program is a sibling, not a branch) ---------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fused_noiw(p, interpret, block_rows, lp, v, olp, ov, rw, mask):
    return _fwd_call(p, interpret, block_rows, (lp, v, olp, ov, rw, mask))


def _fused_noiw_fwd(p, interpret, block_rows, lp, v, olp, ov, rw, mask):
    res = (lp, v, olp, ov, rw, mask)
    return _fwd_call(p, interpret, block_rows, res), res


def _fused_noiw_bwd(p, interpret, block_rows, res, ct):
    lp, v = res[0], res[1]
    dlp, dv = _bwd_call(p, interpret, block_rows, res, ct[0])
    zeros = tuple(jnp.zeros_like(x) for x in res[2:])
    return (dlp.astype(lp.dtype), dv.astype(v.dtype)) + zeros


_fused_noiw.defvjp(_fused_noiw_fwd, _fused_noiw_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fused_iw(p, interpret, block_rows, lp, v, olp, ov, rw, mask, blp):
    return _fwd_call(
        p, interpret, block_rows, (lp, v, olp, ov, rw, mask, blp)
    )


def _fused_iw_fwd(p, interpret, block_rows, lp, v, olp, ov, rw, mask, blp):
    res = (lp, v, olp, ov, rw, mask, blp)
    return _fwd_call(p, interpret, block_rows, res), res


def _fused_iw_bwd(p, interpret, block_rows, res, ct):
    lp, v = res[0], res[1]
    dlp, dv = _bwd_call(p, interpret, block_rows, res, ct[0])
    zeros = tuple(jnp.zeros_like(x) for x in res[2:])
    return (dlp.astype(lp.dtype), dv.astype(v.dtype)) + zeros


_fused_iw.defvjp(_fused_iw_fwd, _fused_iw_bwd)


# --- host entry points --------------------------------------------------


def fused_ppo_loss_reference(
    method,
    logprobs: jax.Array,  # [B, R]
    values: jax.Array,  # [B, R]
    old_logprobs: jax.Array,  # [B, R]
    old_values: jax.Array,  # [B, R]
    rewards: jax.Array,  # [B, R]
    mask: jax.Array,  # [B, R] float response mask
    behavior_logprobs: Optional[jax.Array] = None,
) -> Tuple[jax.Array, dict]:
    """The staged XLA composition — GAE → whiten → loss — exactly as the
    trainer's ``loss_kernel: xla`` path runs it (test reference, and the
    fallback when the Mosaic backend is unavailable)."""
    return _loss_core(
        loss_params_of(method),
        logprobs,
        values,
        old_logprobs,
        old_values,
        rewards,
        mask,
        behavior_logprobs,
    )


def fused_ppo_loss(
    method,
    logprobs: jax.Array,  # [B, R] new per-token logprobs
    values: jax.Array,  # [B, R] new value predictions
    old_logprobs: jax.Array,  # [B, R] proximal-anchor logprobs
    old_values: jax.Array,  # [B, R] rollout values (GAE input + clip anchor)
    rewards: jax.Array,  # [B, R] per-token KL-penalty rewards
    mask: jax.Array,  # [B, R] 1.0 on real response tokens
    behavior_logprobs: Optional[jax.Array] = None,
    *,
    interpret: Optional[bool] = None,
    block_rows: int = 8,
) -> Tuple[jax.Array, dict]:
    """GAE + whitening + clipped PPO loss as one fused Pallas program.

    Returns ``(loss, stats)`` bit-identical — loss, grads (via the paired
    backward kernel), every stat, every ``dist/*`` sketch bin — to
    ``method.get_advantages_and_returns`` followed by ``method.loss``
    (pinned by ``tests/test_fused_loss.py``). Stats come back
    stop-gradient'd; gradients flow through ``loss`` to ``logprobs`` and
    ``values`` only (the rest are batch constants in the trainer).
    """
    p = loss_params_of(method)
    if not has_pallas_tpu():  # pragma: no cover - exotic CPU-only builds
        return fused_ppo_loss_reference(
            method, logprobs, values, old_logprobs, old_values, rewards,
            mask, behavior_logprobs,
        )
    interpret = resolve_interpret(interpret)
    use_iw = behavior_logprobs is not None and p.iw_correction != "off"
    operands = (logprobs, values, old_logprobs, old_values, rewards, mask)
    if use_iw:
        loss, scalars, hists = _fused_iw(
            p, interpret, block_rows, *operands, behavior_logprobs
        )
    else:
        loss, scalars, hists = _fused_noiw(p, interpret, block_rows, *operands)
    scalar_keys, hist_keys = _stat_keys(
        p,
        _shapes_dtypes(operands + ((behavior_logprobs,) if use_iw else ())),
        use_iw,
    )
    stats = {}
    for idx, k in enumerate(scalar_keys):
        stats[k] = jax.lax.stop_gradient(scalars[idx])
    for idx, k in enumerate(hist_keys):
        stats[k] = jax.lax.stop_gradient(hists[idx])
    return loss, stats
