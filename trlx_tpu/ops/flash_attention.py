"""Fused flash attention as a Pallas TPU kernel.

This replaces the materialised ``[B, H, T, S]`` score tensor of the naive XLA
path (``trlx_tpu/models/transformer.py``) for the two training-dominant passes
identified in SURVEY.md §3 — the rollout scoring forward and the train-step
forward/backward. (Single-token decode keeps the einsum path: its score tensor
is ``[B, H, 1, S]`` and HBM-bound either way.) The reference gets the same op
from CUDA fused attention inside HF transformers (SURVEY.md §2.4); here it is
a TPU kernel with an online-softmax forward and a recomputation backward wired
up via ``jax.custom_vjp``.

Design notes:
- Masking is *slot-causal + key-validity*, matching
  ``CausalTransformer._attention_bias``: key slot ``s`` is visible to query
  slot ``t`` iff ``s + k_offset <= t + q_offset`` (when causal) and
  ``key_mask[b, s] > 0``. Offsets make the same kernel serve ring attention
  (``trlx_tpu/parallel/ring_attention.py``), where each device holds one
  rotating chunk of K/V with a different global slot offset.
- ALiBi (BLOOM) is applied in-kernel from per-slot *token positions* (cumsum
  of the mask, computed by the caller) so left-padded prompts get correct
  relative distances.
- The forward also emits the per-row logsumexp ``L``; ``(out, L)`` pairs
  combine associatively, which is exactly what the ring-attention accumulator
  needs.
- f32 accumulation throughout; inputs may be bf16.
- Registered in ``analysis/kernels.py::KERNEL_PARITY`` as ``flash-fwd`` /
  ``flash-bwd``: graftlint's kernel-discipline pass (GL1001–GL1004) keeps
  both entries gated through ``pallas_utils``, the kernel bodies pure, and
  the ``attention_reference`` parity pin alive (docs/STATIC_ANALYSIS.md).
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from trlx_tpu.ops.pallas_utils import (  # noqa: F401  (NEG_INF/LANES re-export)
    LANES,
    NEG_INF,
    pad_to as _pad_to,
    resolve_interpret as _resolve_interpret,
    smem_spec as _smem_spec,
)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    qoff_ref,  # SMEM (1,)
    koff_ref,  # SMEM (1,)
    q_ref,  # (1, 1, bQ, D)
    k_ref,  # (1, 1, Sp, D)
    v_ref,  # (1, 1, Sp, D)
    kmask_ref,  # (1, 1, Sp)
    qpos_ref,  # (1, 1, bQ)
    kpos_ref,  # (1, 1, Sp)
    slopes_ref,  # SMEM (H,) alibi slopes
    o_ref,  # (1, 1, bQ, D)
    l_ref,  # (1, 1, bQ, LANES) lane-replicated logsumexp
    *,
    sm_scale: float,
    causal: bool,
    alibi: bool,
    block_k: int,
    seq_k: int,
    block_q: int,
    window: int,  # sliding-window width in slots (0 = unbounded)
):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # (bQ, D)
    qoff = qoff_ref[0]
    koff = koff_ref[0]
    q_slots = qoff + iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    if alibi:
        q_pos = qpos_ref[0, 0].astype(jnp.float32).reshape(block_q, 1)
        slope = slopes_ref[pl.program_id(1)]

    n_k = seq_k // block_k
    if causal:
        # last k block whose first slot can be visible to any query in this
        # q block: k_slot <= q_slot  ⇔  koff + s <= qoff + (iq+1)*bQ - 1
        hi = jnp.clip(
            (qoff + (iq + 1) * block_q - koff + block_k - 1) // block_k, 0, n_k
        )
    else:
        hi = n_k
    lo = 0
    if window:
        # first k block any query here can see: k_slot > q_slot - window
        lo = jnp.clip((qoff + iq * block_q - (window - 1) - koff) // block_k, 0, hi)

    def body(ik, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(ik * block_k, block_k), :].astype(jnp.float32)
        kmask = kmask_ref[0, 0, pl.ds(ik * block_k, block_k)].reshape(1, block_k)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bQ, bK)
        k_slots = (
            koff
            + ik * block_k
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        )
        visible = kmask > 0.5
        if causal:
            visible = visible & (k_slots <= q_slots)
        if window:
            # slots are laid out in temporal order with padding only on the
            # left, so slot distance ≡ position distance for real pairs
            visible = visible & (q_slots - k_slots < window)
        if alibi:
            k_pos = kpos_ref[0, 0, pl.ds(ik * block_k, block_k)].astype(
                jnp.float32
            ).reshape(1, block_k)
            s = s + slope * (k_pos - q_pos)
        s = jnp.where(visible, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        # exp(NEG_INF - m_new) underflows to 0 unless the whole row is masked
        # (m_new == NEG_INF); the explicit `visible` factor covers that case.
        p = jnp.exp(s - m_new) * visible.astype(jnp.float32)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc = acc * alpha + pv
        return acc, m_new, l

    d = q_ref.shape[-1]
    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(lo, hi, body, (acc, m, l))

    safe_l = jnp.where(l > 0.0, l, 1.0)
    o_ref[0, 0] = (acc / safe_l).astype(o_ref.dtype)
    logsum = jnp.where(l > 0.0, m + jnp.log(safe_l), NEG_INF)
    l_ref[0, 0] = jnp.broadcast_to(logsum, (block_q, LANES))


# ---------------------------------------------------------------------------
# backward (fused: dq + dk + dv in one kernel)
# ---------------------------------------------------------------------------


def _bwd_fused_kernel(
    qoff_ref,
    koff_ref,
    q_ref,  # (1, 1, Tp, D)  full queries
    k_ref,  # (1, 1, bK, D)
    v_ref,  # (1, 1, bK, D)
    kmask_ref,  # (1, 1, bK)
    qpos_ref,  # (1, 1, Tp)
    kpos_ref,  # (1, 1, bK)
    slopes_ref,
    lse_ref,  # (1, 1, Tp, LANES)
    delta_ref,  # (1, 1, Tp, LANES)
    do_ref,  # (1, 1, Tp, D)
    dq_ref,  # (1, 1, Tp, D) f32, accumulated across the k-block grid dim
    dk_ref,  # (1, 1, bK, D)
    dv_ref,  # (1, 1, bK, D)
    *,
    sm_scale: float,
    causal: bool,
    alibi: bool,
    block_q: int,
    seq_q: int,
    block_k: int,
    window: int,  # sliding-window width in slots (0 = unbounded)
):
    """Fused backward: one pass over (k-block × q-blocks) produces dk/dv for
    the k block AND accumulates dq into its full-sequence buffer — the TPU
    grid is sequential per (b, h), so the dq window persists in VMEM across
    k-block steps. Versus the split dq/dkv kernels this computes the s / p /
    dp matmul chain once instead of twice (5 MXU ops per tile pair vs 7)."""
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    k = k_ref[0, 0].astype(jnp.float32)  # (bK, D)
    v = v_ref[0, 0].astype(jnp.float32)
    kmask = kmask_ref[0, 0].reshape(1, block_k)
    qoff = qoff_ref[0]
    koff = koff_ref[0]
    k_slots = koff + ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    if alibi:
        k_pos = kpos_ref[0, 0].astype(jnp.float32).reshape(1, block_k)
        slope = slopes_ref[pl.program_id(1)]

    n_q = seq_q // block_q
    if causal:
        lo = jnp.clip((koff + ik * block_k - qoff) // block_q, 0, n_q)
    else:
        lo = 0
    hi = n_q
    if window:
        # last q block that can still see this k block: q_slot < k_slot + W
        hi = jnp.clip(
            (koff + (ik + 1) * block_k + window - 2 - qoff) // block_q + 1, lo, n_q
        )

    def body(iq, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32) * sm_scale
        do = do_ref[0, 0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(iq * block_q, block_q), 0:1]
        delta = delta_ref[0, 0, pl.ds(iq * block_q, block_q), 0:1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        q_slots = qoff + iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        visible = kmask > 0.5
        if causal:
            visible = visible & (k_slots <= q_slots)
        if window:
            visible = visible & (q_slots - k_slots < window)
        if alibi:
            q_pos = qpos_ref[0, 0, pl.ds(iq * block_q, block_q)].astype(
                jnp.float32
            ).reshape(block_q, 1)
            s = s + slope * (k_pos - q_pos)
        p = jnp.exp(jnp.where(visible, s, NEG_INF) - lse) * visible.astype(
            jnp.float32
        )
        dv_blk = jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bK, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_blk = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bK, D)
        dq_blk = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bQ, D)
        cur = dq_ref[0, 0, pl.ds(iq * block_q, block_q), :]
        dq_ref[0, 0, pl.ds(iq * block_q, block_q), :] = cur + dq_blk * sm_scale
        return dk + dk_blk, dv + dv_blk

    d = q_ref.shape[-1]
    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, hi, body, (zeros, zeros))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11, 12, 13, 14)
)
def _flash(
    q,  # (B, H, T, D)
    k,  # (B, H, S, D)
    v,  # (B, H, S, D)
    kmask,  # (B, 1, S) float
    qpos,  # (B, 1, T) int32
    kpos,  # (B, 1, S) int32
    slopes,  # (H,) float32 (zeros when alibi disabled)
    offsets,  # (q_offset, k_offset) int32 arrays of shape (1,)
    sm_scale: float,
    causal: bool,
    alibi: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    window: int,
):
    out, _ = _flash_fwd_impl(
        q, k, v, kmask, qpos, kpos, slopes, offsets,
        sm_scale, causal, alibi, block_q, block_k, interpret, window,
    )
    return out


def _flash_fwd_impl(
    q, k, v, kmask, qpos, kpos, slopes, offsets,
    sm_scale, causal, alibi, block_q, block_k, interpret, window=0,
):
    B, H, T, D = q.shape
    KV, S = k.shape[1], k.shape[2]
    group = H // KV  # grouped-query attention: q-head h reads kv-head h//group
    qoff, koff = offsets
    grid = (B, H, T // block_q)

    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        alibi=alibi,
        block_k=block_k,
        seq_k=S,
        block_q=block_q,
        window=window,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, h, i: (b, 0, i)),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0)),
            _smem_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qoff, koff, q, k, v, kmask, qpos, kpos, slopes)
    return out, lse


def _flash_fwd_rule(
    q, k, v, kmask, qpos, kpos, slopes, offsets,
    sm_scale, causal, alibi, block_q, block_k, interpret, window,
):
    out, lse = _flash_fwd_impl(
        q, k, v, kmask, qpos, kpos, slopes, offsets,
        sm_scale, causal, alibi, block_q, block_k, interpret, window,
    )
    res = (q, k, v, kmask, qpos, kpos, slopes, offsets, out, lse)
    return out, res


def _bwd_fused_call(
    qoff, koff, q, k, v, kmask, qpos, kpos, slopes, lse, delta, do,
    sm_scale, causal, alibi, block_q, block_k, interpret, window=0,
):
    """Single fused pallas call producing (dq, dk, dv) on kernel-layout
    padded inputs. dq accumulates in f32 across the sequential k-block grid
    (``sm_scale`` applied in-kernel); GQA partials are group-summed here."""
    B, H, T, D = q.shape
    KV, S = k.shape[1], k.shape[2]
    group = H // KV
    kernel = functools.partial(
        _bwd_fused_kernel,
        sm_scale=sm_scale,
        causal=causal,
        alibi=alibi,
        block_q=block_q,
        seq_q=T,
        block_k=block_k,
        window=window,
    )
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(B, H, S // block_k),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            pl.BlockSpec((1, 1, T, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h // group, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h // group, i, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i: (b, 0, i)),
            pl.BlockSpec((1, 1, T), lambda b, h, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i: (b, 0, i)),
            _smem_spec(),
            pl.BlockSpec((1, 1, T, LANES), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, T, LANES), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, T, D), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, T, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), v.dtype),
        ],
        interpret=interpret,
    )(qoff, koff, q, k, v, kmask, qpos, kpos, slopes, lse, delta, do)
    if group > 1:
        dk = dk.reshape(B, KV, group, S, D).sum(axis=2)
        dv = dv.reshape(B, KV, group, S, D).sum(axis=2)
    return dq.astype(q.dtype), dk, dv


def _flash_bwd_rule(
    sm_scale, causal, alibi, block_q, block_k, interpret, window, res, do
):
    q, k, v, kmask, qpos, kpos, slopes, offsets, out, lse = res
    B, H, T, D = q.shape
    qoff, koff = offsets
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (B, H, T)
    delta = jnp.broadcast_to(delta[..., None], (B, H, T, LANES))

    args = (qoff, koff, q, k, v, kmask, qpos, kpos, slopes, lse, delta, do)
    opts = (sm_scale, causal, alibi, block_q, block_k, interpret, window)
    dq, dk, dv = _bwd_fused_call(*args, *opts)

    zeros_like = jax.tree_util.tree_map(jnp.zeros_like, (kmask, qpos, kpos, slopes, offsets))
    return (dq, dk, dv) + zeros_like


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_bwd_chunk(
    q: jax.Array,  # (B, T, H, D) local queries
    k: jax.Array,  # (B, S, H, D) visiting key chunk
    v: jax.Array,  # (B, S, H, D)
    key_mask: jax.Array,  # (B, S)
    lse: jax.Array,  # (B, H, T) GLOBAL logsumexp of the full (ring) softmax
    delta: jax.Array,  # (B, H, T) rowsum(do * out_final)
    do: jax.Array,  # (B, T, H, D) cotangent of the final output
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    q_positions: Optional[jax.Array] = None,  # (B, T) for alibi
    k_positions: Optional[jax.Array] = None,  # (B, S) for alibi
    alibi_slopes: Optional[jax.Array] = None,  # (H,)
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,  # sliding-window width (None = unbounded)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One (q-chunk × kv-chunk) term of the flash backward, in model layout.

    With the *global* ``lse``/``delta``, summing these terms over all kv
    chunks (rotating around the ring) reproduces the exact monolithic
    backward — this is the building block of the ring-attention VJP
    (``trlx_tpu/parallel/ring_attention.py``). One fused kernel call
    produces all three grads.
    """
    interpret = _resolve_interpret(interpret)
    B, T, H, D = q.shape
    S = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    alibi = alibi_slopes is not None
    if interpret:
        block_q = min(block_q, max(T, 8))
        block_k = min(block_k, max(S, 8))

    qt = _pad_to(q.transpose(0, 2, 1, 3), block_q, 2)
    kt = _pad_to(k.transpose(0, 2, 1, 3), block_k, 2)
    vt = _pad_to(v.transpose(0, 2, 1, 3), block_k, 2)
    dot = _pad_to(do.transpose(0, 2, 1, 3), block_q, 2)
    Tp, Sp = qt.shape[2], kt.shape[2]
    kmask = _pad_to(key_mask.astype(jnp.float32), block_k, 1).reshape(B, 1, Sp)
    if q_positions is None:
        q_positions = jnp.zeros((B, T), jnp.int32)
    if k_positions is None:
        k_positions = jnp.zeros((B, S), jnp.int32)
    qpos = _pad_to(q_positions.astype(jnp.int32), block_q, 1).reshape(B, 1, Tp)
    kpos = _pad_to(k_positions.astype(jnp.int32), block_k, 1).reshape(B, 1, Sp)
    slopes = (
        alibi_slopes.astype(jnp.float32).reshape(H)
        if alibi
        else jnp.zeros((H,), jnp.float32)
    )
    # padded query rows: a +inf-like lse sentinel drives p = exp(s - 1e30) to
    # zero regardless of which keys the padded slots would "see" (a NEG_INF
    # sentinel would instead overflow p to inf for visible pairs)
    lse_p = _pad_to(lse, block_q, 2)
    lse_p = jnp.where(
        jnp.arange(Tp)[None, None, :] < T, lse_p, -NEG_INF
    )
    lse_p = jnp.broadcast_to(lse_p[..., None], (B, H, Tp, LANES))
    delta_p = jnp.broadcast_to(_pad_to(delta, block_q, 2)[..., None], (B, H, Tp, LANES))
    offsets = (
        jnp.asarray(q_offset, jnp.int32).reshape(1),
        jnp.asarray(k_offset, jnp.int32).reshape(1),
    )

    args = (offsets[0], offsets[1], qt, kt, vt, kmask, qpos, kpos, slopes, lse_p, delta_p, dot)
    opts = (sm_scale, causal, alibi, block_q, block_k, interpret, int(window or 0))
    dq, dk, dv = _bwd_fused_call(*args, *opts)
    return (
        dq[:, :, :T, :].transpose(0, 2, 1, 3),
        dk[:, :, :S, :].transpose(0, 2, 1, 3),
        dv[:, :, :S, :].transpose(0, 2, 1, 3),
    )


def flash_attention(
    q: jax.Array,  # (B, T, H, D)
    k: jax.Array,  # (B, S, H, D)
    v: jax.Array,  # (B, S, H, D)
    key_mask: jax.Array,  # (B, S) 1 = valid slot
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    q_positions: Optional[jax.Array] = None,  # (B, T) for alibi
    k_positions: Optional[jax.Array] = None,  # (B, S) for alibi
    alibi_slopes: Optional[jax.Array] = None,  # (H,)
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    return_lse: bool = False,
    window: Optional[int] = None,  # sliding-window width (None = unbounded)
):
    """Flash attention over ``[B, T, H, D]`` tensors (model layout).

    Pads T/S up to block multiples internally; padded key slots are invisible
    (mask 0), padded query rows produce zeros and are sliced off. With
    ``return_lse`` the per-row logsumexp over *unpadded* rows is returned too
    (needed by the ring-attention combiner). NOTE: the ``return_lse`` variant
    is forward-only (no VJP is defined for the pair); ring attention defines
    its own VJP over whole ring sweeps rather than differentiating per-chunk
    (out, lse) pairs.
    """
    interpret = _resolve_interpret(interpret)
    B, T, H, D = q.shape
    S, KV = k.shape[1], k.shape[2]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    alibi = alibi_slopes is not None

    if interpret:
        # interpreter has no tiling constraints; small blocks keep CPU tests fast
        block_q = min(block_q, max(T, 8))
        block_k = min(block_k, max(S, 8))
    # on hardware, blocks stay tile-aligned (128) and T/S are padded up to a
    # block multiple below — Mosaic rejects sub-128 lane blocks

    # [B, T, H, D] → [B, H, T, D]
    qt = _pad_to(q.transpose(0, 2, 1, 3), block_q, 2)
    kt = _pad_to(k.transpose(0, 2, 1, 3), block_k, 2)
    vt = _pad_to(v.transpose(0, 2, 1, 3), block_k, 2)
    Tp, Sp = qt.shape[2], kt.shape[2]

    kmask = _pad_to(key_mask.astype(jnp.float32), block_k, 1).reshape(B, 1, Sp)
    if q_positions is None:
        q_positions = jnp.zeros((B, T), jnp.int32)
    if k_positions is None:
        k_positions = jnp.zeros((B, S), jnp.int32)
    qpos = _pad_to(q_positions.astype(jnp.int32), block_q, 1).reshape(B, 1, Tp)
    kpos = _pad_to(k_positions.astype(jnp.int32), block_k, 1).reshape(B, 1, Sp)
    slopes = (
        alibi_slopes.astype(jnp.float32).reshape(H)
        if alibi
        else jnp.zeros((H,), jnp.float32)
    )
    offsets = (
        jnp.asarray(q_offset, jnp.int32).reshape(1),
        jnp.asarray(k_offset, jnp.int32).reshape(1),
    )

    win = int(window or 0)
    if return_lse:
        out, lse = _flash_fwd_impl(
            qt, kt, vt, kmask, qpos, kpos, slopes, offsets,
            sm_scale, causal, alibi, block_q, block_k, interpret, win,
        )
        return (
            out[:, :, :T, :].transpose(0, 2, 1, 3),
            lse[:, :, :T, 0],
        )
    out = _flash(
        qt, kt, vt, kmask, qpos, kpos, slopes, offsets,
        sm_scale, causal, alibi, block_q, block_k, interpret, win,
    )
    return out[:, :, :T, :].transpose(0, 2, 1, 3)


def attention_reference(
    q, k, v, key_mask, *, causal=True, sm_scale=None,
    q_offset=0, k_offset=0, q_positions=None, k_positions=None,
    alibi_slopes=None, window=None,
) -> Tuple[jax.Array, jax.Array]:
    """Naive XLA attention with identical masking semantics (test oracle).

    Returns (out, logsumexp), both f32-accumulated.
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    s = jnp.einsum(
        "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * sm_scale
    visible = key_mask[:, None, None, :] > 0.5
    q_slots = jnp.arange(T)[:, None] + jnp.asarray(q_offset)
    k_slots = jnp.arange(S)[None, :] + jnp.asarray(k_offset)
    if causal:
        visible = visible & (k_slots <= q_slots)[None, None, :, :]
    if window:
        visible = visible & (q_slots - k_slots < window)[None, None, :, :]
    if alibi_slopes is not None:
        dist = (
            k_positions[:, None, :] - q_positions[:, :, None]
        ).astype(jnp.float32)
        s = s + alibi_slopes.astype(jnp.float32)[None, :, None, None] * dist[:, None]
    s = jnp.where(visible, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m) * visible.astype(jnp.float32)
    l = jnp.sum(p, axis=-1, keepdims=True)
    safe_l = jnp.where(l > 0, l, 1.0)
    out = jnp.einsum("bhts,bshd->bthd", p / safe_l, v.astype(jnp.float32))
    lse = jnp.where(l > 0, m + jnp.log(safe_l), NEG_INF)[..., 0]
    return out, lse
