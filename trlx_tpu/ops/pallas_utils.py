"""Shared Pallas TPU plumbing for the repo's kernels.

Every Pallas kernel module (``ops/flash_attention.py``,
``ops/paged_attention.py``) needs the same three decisions made the same
way, so they live here once:

- **Backend probe**: ``jax.experimental.pallas.tpu`` (Mosaic) is absent on
  some CPU-only builds; kernels must import it guardedly and degrade to
  generic Pallas (``pl.ANY`` memory spaces) when it is missing.
- **Interpret-mode default**: off-TPU, kernels run under the Pallas
  interpreter — the same kernel body executed as traced jax ops, which is
  what makes the CPU tier-1 bit-parity tests meaningful (interpret-mode
  ops are ordinary XLA ops on the same values).
- **SMEM spec**: scalar operands live in SMEM on hardware; interpret mode
  (and pltpu-less builds) take ``pl.ANY``.

Masking convention shared by the kernels: masked scores are driven to
``NEG_INF`` (or carry the dense path's ``-1e9`` additive bias) so that
``exp(masked - max)`` underflows to exactly ``0.0`` — which is what makes
recycled-block stale values contribute nothing to paged attention and
padded key slots contribute nothing to flash attention.
"""

from typing import Optional

import jax
from jax.experimental import pallas as pl

try:  # the pallas TPU backend is unavailable on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = [
    "pltpu",
    "NEG_INF",
    "LANES",
    "has_pallas_tpu",
    "default_interpret",
    "resolve_interpret",
    "smem_spec",
    "pad_to",
    "align_rows",
    "clamp_block_table",
    "pad_bias_to",
    "paged_pool_grid_spec",
]

NEG_INF = -1e30
# lane width for per-row stats (lse/delta/sampled token); 8 is the f32
# sublane minimum and the "equal to the overall array dim" rule makes the
# last dim legal
LANES = 8


def has_pallas_tpu() -> bool:
    """True when the Mosaic (pallas TPU) backend is importable."""
    return _HAS_PLTPU


def default_interpret() -> bool:
    """Kernels compile for real only on TPU; every other backend runs the
    Pallas interpreter (bit-parity tests pin the interpret path on CPU)."""
    return jax.default_backend() != "tpu"


def resolve_interpret(interpret: Optional[bool]) -> bool:
    """The per-call ``interpret=`` knob: ``None`` = backend default."""
    return default_interpret() if interpret is None else bool(interpret)


def smem_spec() -> pl.BlockSpec:
    """Whole-operand scalar spec: SMEM on hardware, ANY elsewhere."""
    if _HAS_PLTPU:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(memory_space=pl.ANY)


def pad_to(x: jax.Array, mult: int, axis: int) -> jax.Array:
    """Zero-pad ``axis`` up to the next multiple of ``mult``."""
    import jax.numpy as jnp

    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def align_rows(n: int, interpret: bool, lanes: int = 128) -> int:
    """Scratch/operand row count for a VMEM buffer: exact under the
    interpreter, rounded up to the hardware lane multiple on chip. Kernels
    read ``[0:n]`` slices either way, so alignment never changes bits."""
    return n if interpret else -(-n // lanes) * lanes


def clamp_block_table(block_table: jax.Array, num_blocks: int) -> jax.Array:
    """Block-table ids as safe int32 fetch indices: out-of-range entries
    (poisoned rows, frozen slots) clamp to the last pool block — their
    lanes are bias-masked or their outputs dropped, so the clamped fetch
    only has to be *legal*, never correct."""
    import jax.numpy as jnp

    return jnp.minimum(block_table.astype(jnp.int32), num_blocks - 1)


def pad_bias_to(bias: jax.Array, width: int) -> jax.Array:
    """Additive bias as the kernels consume it: f32, last (key) axis
    zero-padded to exactly ``width`` (the block-table span). Padded columns
    sit beyond ``seq_len`` and are never read by the compute slice."""
    import jax.numpy as jnp

    bias = bias.astype(jnp.float32)
    short = width - bias.shape[-1]
    if short <= 0:
        return bias
    widths = [(0, 0)] * (bias.ndim - 1) + [(0, short)]
    return jnp.pad(bias, widths)


def _row_block_spec(block) -> pl.BlockSpec:
    """Per-row spec under the ``(b, j, tbl)`` paged grid: block ``b`` along
    the leading (batch) axis, whole operand elsewhere."""
    zeros = (0,) * (len(block) - 1)
    return pl.BlockSpec(block, lambda b, j, tbl: (b,) + zeros)


def paged_pool_grid_spec(
    *,
    batch: int,
    table_blocks: int,
    block_size: int,
    kv_heads: int,
    head_dim: int,
    q_block,
    bias_block,
    out_block,
    scratch_rows: int,
    k_dtype,
    v_dtype,
):
    """The shared scalar-prefetch grid for pool-reading kernels.

    ``ops/paged_attention.py`` and ``ops/paged_prefill.py`` (and the verify
    entry built on the latter) all walk the same ``(B, TB)`` grid in which
    the scalar-prefetched block table *is* the K/V index map: grid cell
    ``(b, j)`` fetches pool block ``tbl[b, j]`` into VMEM, and per-row
    operands (q / bias / out) ride the batch axis. Factored here so the
    fourth kernel doesn't carry the fourth copy of this boilerplate
    (ISSUE 18) — the shape differences between decode (``q: (1, H, D)``)
    and prefill (``q: (1, T, H, D)``) are entirely in the block tuples.
    """
    if not _HAS_PLTPU:  # pragma: no cover - callers gate on has_pallas_tpu
        raise RuntimeError(
            "paged_pool_grid_spec requires the Mosaic (pallas TPU) backend"
        )
    pool_block = (1, block_size, kv_heads, head_dim)

    def pool_map(b, j, tbl):
        return (tbl[b, j], 0, 0, 0)

    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch, table_blocks),
        in_specs=[
            _row_block_spec(q_block),
            _row_block_spec(bias_block),
            pl.BlockSpec(pool_block, pool_map),
            pl.BlockSpec(pool_block, pool_map),
        ],
        out_specs=_row_block_spec(out_block),
        scratch_shapes=[
            pltpu.VMEM((scratch_rows, kv_heads, head_dim), k_dtype),
            pltpu.VMEM((scratch_rows, kv_heads, head_dim), v_dtype),
        ],
    )
