"""Shared Pallas TPU plumbing for the repo's kernels.

Every Pallas kernel module (``ops/flash_attention.py``,
``ops/paged_attention.py``) needs the same three decisions made the same
way, so they live here once:

- **Backend probe**: ``jax.experimental.pallas.tpu`` (Mosaic) is absent on
  some CPU-only builds; kernels must import it guardedly and degrade to
  generic Pallas (``pl.ANY`` memory spaces) when it is missing.
- **Interpret-mode default**: off-TPU, kernels run under the Pallas
  interpreter — the same kernel body executed as traced jax ops, which is
  what makes the CPU tier-1 bit-parity tests meaningful (interpret-mode
  ops are ordinary XLA ops on the same values).
- **SMEM spec**: scalar operands live in SMEM on hardware; interpret mode
  (and pltpu-less builds) take ``pl.ANY``.

Masking convention shared by the kernels: masked scores are driven to
``NEG_INF`` (or carry the dense path's ``-1e9`` additive bias) so that
``exp(masked - max)`` underflows to exactly ``0.0`` — which is what makes
recycled-block stale values contribute nothing to paged attention and
padded key slots contribute nothing to flash attention.
"""

from typing import Optional

import jax
from jax.experimental import pallas as pl

try:  # the pallas TPU backend is unavailable on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = [
    "pltpu",
    "NEG_INF",
    "LANES",
    "has_pallas_tpu",
    "default_interpret",
    "resolve_interpret",
    "smem_spec",
    "pad_to",
]

NEG_INF = -1e30
# lane width for per-row stats (lse/delta/sampled token); 8 is the f32
# sublane minimum and the "equal to the overall array dim" rule makes the
# last dim legal
LANES = 8


def has_pallas_tpu() -> bool:
    """True when the Mosaic (pallas TPU) backend is importable."""
    return _HAS_PLTPU


def default_interpret() -> bool:
    """Kernels compile for real only on TPU; every other backend runs the
    Pallas interpreter (bit-parity tests pin the interpret path on CPU)."""
    return jax.default_backend() != "tpu"


def resolve_interpret(interpret: Optional[bool]) -> bool:
    """The per-call ``interpret=`` knob: ``None`` = backend default."""
    return default_interpret() if interpret is None else bool(interpret)


def smem_spec() -> pl.BlockSpec:
    """Whole-operand scalar spec: SMEM on hardware, ANY elsewhere."""
    if _HAS_PLTPU:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(memory_space=pl.ANY)


def pad_to(x: jax.Array, mult: int, axis: int) -> jax.Array:
    """Zero-pad ``axis`` up to the next multiple of ``mult``."""
    import jax.numpy as jnp

    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)
