"""Speculative decoding for rollout generation (draft-and-verify).

Beyond the reference (whose generation hot loop is plain HF ``generate``,
SURVEY.md §3.2): a small draft model proposes ``gamma`` tokens
autoregressively, the target model scores all of them in ONE forward, and a
rejection-sampling acceptance rule keeps a prefix — provably sampling from
the target distribution (Leviathan et al. 2023; Chen et al. 2023). Per
round the target runs one length-``gamma+1`` forward instead of up to
``gamma+1`` single-token decodes, so rollout wall-clock approaches the
draft's cost when the draft approximates the target well.

TPU-first structure: the whole sampler is one jitted program — a
``lax.while_loop`` over rounds with static shapes throughout. Rows accept
different prefix lengths, so both KV caches use per-row write indices (the
``[B]``-vector ``cache_index`` path of ``models/transformer.py::Attention``)
and committed-token bookkeeping is per row. Rounds are stateless: each
starts by re-feeding the last committed token (whose K/V the caches lack —
it was sampled from a residual/bonus distribution, never forwarded), which
also re-derives both models' next-token distributions, so no logits are
carried across rounds and cache rewinds are just index arithmetic.

Exactness properties (tested in ``tests/test_speculative.py``):

- greedy (``do_sample=False``) output is bit-identical to the plain
  sampler's greedy output, for ANY draft;
- with draft == target every proposal is accepted (acceptance ratio 1);
- returned logprobs/values are the TARGET's, with the same semantics as
  :func:`trlx_tpu.ops.sampling.generate` (behavior logprob of the chosen
  token under the unfiltered target distribution; value of the state the
  token was sampled from), so PPO's ``make_experience`` is agnostic to
  which sampler produced the rollout;
- ``per_row_rng=True`` threads [B, 2] per-row key chains through every
  draw site (draft proposals, acceptance uniforms, residual/bonus), so a
  batched run is BIT-IDENTICAL per row to running each row alone with its
  chain — batch composition invariance, the property continuous batching
  needs to host a speculative slot (ROADMAP item 2's named blocker,
  removed). The per-row sampled streams differ from the batch-wide mode's
  by construction; both are exact draws from the target distribution.

Transition logit masks (the trainer's ``logit_mask``, e.g. randomwalks'
allowed-moves table) compose natively: the mask is applied to the draft AND
the target distributions, so constrained sampling stays lossless. So does
``min_new_tokens``: eos is blocked per ROW at response positions below the
minimum — on the draft proposals and on the target's verify distributions
alike, before both sampling and the behavior logprob — exactly the plain
sampler's semantics. And so does the full ``adjust_logits`` hook (ILQL's
Q-value reshaping): it is applied to the target's per-position verify
outputs — sampling is exact w.r.t. the ADJUSTED target distribution, and
the (unadjusted) draft's mismatch only costs acceptance rate.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from trlx_tpu.ops.sampling import (
    _NON_CARRY_KEYS,
    GenerationConfig,
    GenerationOutput,
    apply_transition_mask,
    per_row_keys,
    process_logits,
    split_row_keys,
)


def _filtered_probs(logits: jax.Array, config: GenerationConfig) -> jax.Array:
    """The actual sampling distribution: temperature/top-k/top-p filtered
    softmax (matches ``sample_token_from_logits``'s sampling path)."""
    return jax.nn.softmax(
        process_logits(logits, config.temperature, config.top_k, config.top_p),
        axis=-1,
    )


def accept_and_extra(
    p_probs: jax.Array,  # [B, G+1, V] target dists p_0..p_G
    q_probs: jax.Array,  # [B, G, V] draft dists q_1..q_G
    d_toks: jax.Array,  # [B, G] draft proposals (d_i ~ q_i)
    rng: jax.Array,
    do_sample: bool,
):
    """The speculative acceptance rule as a pure function of distributions.

    Returns ``(k, extra_tok, rng)``: ``k`` accepted draft tokens (the
    committed block is ``d_1..d_k, extra``), the residual/bonus ``extra``
    token, and the advanced rng (callers must thread it — reusing the input
    rng would correlate later draws with the acceptance draws).
    Sampling: accept ``d_i`` iff ``u·q_i(d_i) < p_{i-1}(d_i)``; on the first
    rejection resample from ``norm(max(p−q, 0))``; after a full accept,
    sample the bonus from ``p_G``. This is the Leviathan/Chen rejection
    scheme — the marginal of every committed token is EXACTLY the target's
    (machine-checked against enumerated distributions in
    ``tests/test_speculative.py::test_acceptance_rule_is_distribution_exact``).
    Greedy: accept iff ``d_i == argmax p_{i-1}``; extra = ``argmax p_k``.

    ``rng`` may be one batch-wide key (``[2]``, historical behavior) or a
    ``[B, 2]`` stack of per-row key chains (``per_row_rng``): each row then
    draws its acceptance uniforms and residual/bonus token from its OWN
    chain — one ``split_row_keys`` advance per draw site, so a row's
    stream depends only on (its chain, its round), never on batch
    composition. That is what makes a batched per-row run bit-identical to
    running each row alone (the B=1-loop parity test).
    """
    B, G = d_toks.shape
    per_row = rng.ndim == 2
    q_sel = jnp.take_along_axis(q_probs, d_toks[..., None], axis=-1)[..., 0]
    p_sel = jnp.take_along_axis(
        p_probs[:, :G, :], d_toks[..., None], axis=-1
    )[..., 0]  # p_{i-1}(d_i)
    if do_sample:
        if per_row:
            rng, ru = split_row_keys(rng)
            u = jax.vmap(lambda kk: jax.random.uniform(kk, (G,)))(ru)
        else:
            rng, ru = jax.random.split(rng)
            u = jax.random.uniform(ru, (B, G))
        # strict <: u ∈ [0,1) can be exactly 0, and `0·q <= 0` would accept
        # a token with ZERO target probability. Accept iff u < p/q.
        accept = u * q_sel < p_sel
    else:
        accept = d_toks == jnp.argmax(p_probs[:, :G, :], axis=-1)
    k = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

    p_row_at_k = jnp.take_along_axis(p_probs, k[:, None, None], axis=1)[:, 0, :]
    if do_sample:
        res_probs = jnp.maximum(p_probs[:, :G, :] - q_probs, 0.0)  # [B, G, V]
        res_at_k = jnp.take_along_axis(
            res_probs, jnp.minimum(k, G - 1)[:, None, None], axis=1
        )[:, 0, :]
        res_sum = jnp.sum(res_at_k, axis=-1, keepdims=True)
        # bonus (k == G) samples p_G; degenerate residual (p == q exactly)
        # also falls back to p — both are distribution-exact
        extra_dist = jnp.where(
            (k[:, None] < G) & (res_sum > 1e-20),
            res_at_k / jnp.maximum(res_sum, 1e-20),
            p_row_at_k,
        )
        extra_logits = jnp.log(jnp.maximum(extra_dist, 1e-30))
        if per_row:
            rng, re = split_row_keys(rng)
            extra_tok = jax.vmap(
                lambda kk, row: jax.random.categorical(kk, row)
            )(re, extra_logits).astype(jnp.int32)
        else:
            rng, re = jax.random.split(rng)
            extra_tok = jax.random.categorical(
                re, extra_logits, axis=-1
            ).astype(jnp.int32)
    else:
        # greedy: the target would deterministically pick argmax p_k
        extra_tok = jnp.argmax(p_row_at_k, axis=-1).astype(jnp.int32)
    return k, extra_tok, rng


def spec_round_step(
    carry: dict,
    *,
    prompt_mask: jax.Array,  # [B, P] int32
    target_apply: Callable[..., Any],
    target_params: Any,
    draft_apply: Callable[..., Any],
    draft_params: Any,
    config: GenerationConfig,
    G: int,
    transition_mask: Optional[jax.Array] = None,
    adjust_logits: Optional[Callable[[Any, jax.Array], jax.Array]] = None,
) -> dict:
    """One draft-propose → verify → accept round over the shared carry.

    THE speculative round: both ``generate_speculative``'s while_loop body
    and the continuous-batching spec segment's round body
    (``ops/slot_refill.py``) are this one function, so a slot's token
    stream is bit-identical to a solo run by construction rather than by
    mirrored code. The contract that makes that hold across refills and
    batch composition:

    - caches must span ``S = P + N + G`` slots (the solo width — masked
      columns contribute exact-0.0 softmax, but a narrower key axis
      changes the dots' lowering, see ``_make_prefill_chunk``);
    - every forward masks exactly committed slots + the round's ``G``
      probe slots ``[c, c+G)`` — slot-causality inside the model keeps
      everything else (stale pool values included) invisible;
    - the rng chain advances a FIXED number of ``split_row_keys`` draws
      per round (G proposal draws + 2 acceptance draws when sampling),
      so a row's stream depends only on (its chain, its round index).

    Carry keys: ``rng`` ([B,2] per-row chains or [2] batch-wide), ``n_out``
    [B] committed generated tokens, ``done`` [B], ``t_last`` [B] (last
    committed token — its K/V is re-derived by re-feeding, never carried),
    ``t_cache``/``d_cache``, output buffers ``tokens``/``logprobs``/
    ``values``/``mask`` [B, N+G+1], and the scalar counters ``rounds``/
    ``accepted``/``live_rounds``/``committed``.
    """
    B, P = prompt_mask.shape
    N = config.max_new_tokens
    NB = N + G + 1
    V_pad = config.pad_token_id
    per_row = jnp.asarray(carry["rng"]).ndim == 2

    rng = carry["rng"]
    n_out = carry["n_out"]  # [B] committed generated tokens
    done = carry["done"]
    t_last = carry["t_last"]  # [B] last committed token (slot c-1)
    c = P + n_out  # [B] next free slot per row

    # slot mask for this round's forwards: committed slots + the G
    # proposal slots [c, c+G) — slot-causality inside the models keeps
    # stale/future slots invisible to each query
    gen_slots = jnp.arange(NB - 1)[None, :]
    committed = jnp.concatenate(
        [prompt_mask, (gen_slots < n_out[:, None]).astype(jnp.int32)], axis=1
    )
    probe = (gen_slots >= n_out[:, None]) & (gen_slots < (n_out + G)[:, None])
    mask_round = committed + jnp.concatenate(
        [jnp.zeros((B, P), jnp.int32), probe.astype(jnp.int32)], axis=1
    )

    # ---- draft proposes G tokens (G single-token forwards, unrolled:
    # G is small and static) ----
    d_cache_r, tok_r = carry["d_cache"], t_last
    d_toks = jnp.zeros((B, G), jnp.int32)
    # [B, G, V] full draft dists for the residual resample — f32: the
    # rejection-sampling identity needs the SAME q as the accept test
    # (a rounded copy would sample the extra token from rounding noise
    # when p ≈ q, precisely the good-draft case)
    q_probs = None
    for j in range(G):
        prev = tok_r  # the token being fed — q_{j+1} conditions on it
        out_j = draft_apply(
            draft_params, tok_r[:, None], attention_mask=mask_round,
            positions=None, cache=d_cache_r, cache_index=c - 1 + j,
        )
        logits_j = out_j["logits"][:, -1, :].astype(jnp.float32)
        if transition_mask is not None:
            logits_j = apply_transition_mask(transition_mask, prev, logits_j)
        if config.eos_token_id is not None and config.min_new_tokens > 0:
            # proposal j lands at response position n_out + j: block eos
            # there exactly like the plain sampler (q then matches the
            # distribution the proposal is actually drawn from)
            block_j = (n_out + j) < config.min_new_tokens  # [B]
            logits_j = jnp.where(
                block_j[:, None]
                & (jnp.arange(logits_j.shape[-1])[None, :] == config.eos_token_id),
                -jnp.inf,
                logits_j,
            )
        probs_j = _filtered_probs(logits_j, config)
        if per_row:
            rng, rj = split_row_keys(rng)
        else:
            rng, rj = jax.random.split(rng)
        if config.do_sample:
            log_probs_j = jnp.log(jnp.maximum(probs_j, 1e-30))
            if per_row:
                tok_r = jax.vmap(
                    lambda kk, row: jax.random.categorical(kk, row)
                )(rj, log_probs_j).astype(jnp.int32)
            else:
                tok_r = jax.random.categorical(
                    rj, log_probs_j, axis=-1
                ).astype(jnp.int32)
        else:
            tok_r = jnp.argmax(probs_j, axis=-1).astype(jnp.int32)
        if q_probs is None:
            q_probs = jnp.zeros((B, G) + probs_j.shape[-1:], jnp.float32)
        d_toks = d_toks.at[:, j].set(tok_r)
        q_probs = q_probs.at[:, j].set(probs_j)
        d_cache_r = out_j["cache"]
    # one more draft forward to write d_G's K/V (logits discarded):
    # after a fully-accepted round the NEXT round marks d_G's slot
    # committed, and a zero-K/V hole there would quietly degrade every
    # subsequent proposal — exactly in the high-acceptance regime
    d_cache_new = draft_apply(
        draft_params, tok_r[:, None], attention_mask=mask_round,
        positions=None, cache=d_cache_r, cache_index=c - 1 + G,
        logits_span=(0, 0),
    )["cache"]

    # ---- one target forward verifies everything ----
    verify_in = jnp.concatenate([t_last[:, None], d_toks], axis=1)  # [B, G+1]
    t_out = target_apply(
        target_params, verify_in, attention_mask=mask_round,
        positions=None, cache=carry["t_cache"], cache_index=c - 1,
    )
    t_cache_new = t_out["cache"]
    t_logits = t_out["logits"].astype(jnp.float32)  # [B, G+1, V]
    if adjust_logits is not None:
        # same order as the plain sampler: algo reshaping first, then
        # transition mask, then min_new_tokens eos blocking. step_info
        # mirrors the plain sampler's step_out keys (incl. last_tokens),
        # but fields keep the verify shape [B, G+1, ...] where plain
        # passes last-position [B, ...] views — hence the hook contract:
        # leading-dim polymorphic (see BaseRLTrainer.adjust_logits_fn)
        step_info = {
            k: v for k, v in t_out.items()
            if k not in _NON_CARRY_KEYS and v is not None
        }
        step_info["last_tokens"] = verify_in  # token position j conditions on
        t_logits = adjust_logits(step_info, t_logits)
    if transition_mask is not None:
        # p_j conditions on verify position j's input token — identical
        # masking to the plain sampler's logit-mask hook, so behavior
        # logprobs below come from the same (masked) distribution
        t_logits = apply_transition_mask(transition_mask, verify_in, t_logits)
    if config.eos_token_id is not None and config.min_new_tokens > 0:
        # verify position j produces response position n_out + j; the
        # plain sampler blocks eos there BEFORE both sampling and the
        # behavior logprob, so the mask goes on t_logits (feeding
        # p_probs and t_logprobs_all alike) for exactness
        pos = n_out[:, None] + jnp.arange(G + 1)[None, :]  # [B, G+1]
        t_logits = jnp.where(
            (pos < config.min_new_tokens)[..., None]
            & (
                jnp.arange(t_logits.shape[-1])[None, None, :]
                == config.eos_token_id
            ),
            -jnp.inf,
            t_logits,
        )
    p_probs = _filtered_probs(t_logits, config)  # p_0 .. p_G
    t_logprobs_all = jax.nn.log_softmax(t_logits, axis=-1)
    t_values = t_out.get("value")
    if t_values is None:
        t_values = jnp.zeros(verify_in.shape, jnp.float32)
    t_values = t_values.astype(jnp.float32)  # [B, G+1]

    # ---- acceptance (the pure rejection-sampling rule) ----
    k, extra_tok, rng = accept_and_extra(
        p_probs, q_probs, d_toks, rng, config.do_sample
    )

    # ---- tentative committed block: d_1..d_k, extra ----
    j_iota = jnp.arange(G + 1)[None, :]
    block_toks = jnp.concatenate([d_toks, jnp.zeros((B, 1), jnp.int32)], axis=1)
    block_toks = jnp.where(j_iota == k[:, None], extra_tok[:, None], block_toks)
    block_lp = jnp.take_along_axis(
        t_logprobs_all, block_toks[..., None], axis=-1
    )[..., 0]  # log p_j(x_j) — target logprob of each committed token
    block_val = t_values  # v before sampling x_j is at index j

    valid = j_iota <= k[:, None]
    # respect the N budget and prior completion
    valid = valid & ((n_out[:, None] + j_iota) < N) & (~done[:, None])
    if config.eos_token_id is not None:
        is_eos = block_toks == config.eos_token_id
        eos_before = jnp.cumsum(
            jnp.pad(is_eos.astype(jnp.int32), ((0, 0), (1, 0)))[:, :-1], axis=1
        )
        valid = valid & (eos_before == 0)
    commit_len = jnp.sum(valid.astype(jnp.int32), axis=1)  # [B]
    block_toks_w = jnp.where(valid, block_toks, V_pad)
    block_lp_w = jnp.where(valid, block_lp, 0.0)
    block_val_w = jnp.where(valid, block_val, 0.0)
    block_mask_w = valid.astype(jnp.int32)

    # ---- per-row block write into the output buffers ----
    def row_write(buf, blk, i):
        return jax.vmap(
            lambda b, x, o: jax.lax.dynamic_update_slice(b, x.astype(b.dtype), (o,))
        )(buf, blk, i)

    # never write past the buffer; done rows re-write pads over pads
    off = jnp.minimum(n_out, NB - (G + 1))
    tokens = row_write(carry["tokens"], block_toks_w, off)
    logprobs = row_write(carry["logprobs"], block_lp_w, off)
    values = row_write(carry["values"], block_val_w, off)
    out_mask = row_write(carry["mask"], block_mask_w, off)

    n_new = n_out + commit_len
    done_new = done | (n_new >= N)
    if config.eos_token_id is not None:
        done_new = done_new | jnp.any(
            (block_toks_w == config.eos_token_id) & (valid), axis=1
        )
    last_idx = jnp.maximum(commit_len - 1, 0)
    t_last_new = jnp.where(
        commit_len > 0,
        jnp.take_along_axis(block_toks_w, last_idx[:, None], axis=1)[:, 0],
        t_last,
    )

    return {
        "rng": rng,
        "n_out": n_new,
        "done": done_new,
        "t_last": t_last_new,
        "t_cache": t_cache_new,
        "d_cache": d_cache_new,
        "tokens": tokens,
        "logprobs": logprobs,
        "values": values,
        "mask": out_mask,
        "rounds": carry["rounds"] + 1,
        # accepted draft tokens this round, live rows only — k is
        # PRE-truncation acceptance (budget/eos clipping is not
        # rejection), so the rate reflects draft quality alone
        "accepted": carry["accepted"] + jnp.sum(jnp.where(~done, k, 0)),
        "live_rounds": carry["live_rounds"] + jnp.sum((~done).astype(jnp.int32)),
        # tokens actually committed (post budget/eos truncation) — the
        # tokens-per-round throughput numerator
        "committed": carry["committed"] + jnp.sum(jnp.where(~done, commit_len, 0)),
    }


def generate_speculative(
    target_apply: Callable[..., Any],
    target_params: Any,
    draft_apply: Callable[..., Any],
    draft_params: Any,
    init_target_cache: Callable[[int, int], Any],
    init_draft_cache: Callable[[int, int], Any],
    input_ids: jax.Array,  # [B, P] left-padded prompts
    attention_mask: jax.Array,  # [B, P]
    rng: jax.Array,
    config: GenerationConfig,
    gamma: int = 4,
    return_stats: bool = False,
    transition_mask: Optional[jax.Array] = None,  # [Vm, Vm'] bool: the
    # trainer's prev→next logit mask; applied identically to draft AND
    # target so constrained sampling (e.g. randomwalks) stays lossless
    adjust_logits: Optional[Callable[[Any, jax.Array], jax.Array]] = None,
    # algorithm logit reshaping (ILQL: log π + β(minQ − V)) applied to the
    # TARGET's verify distributions — step_out carries the target forward's
    # per-position outputs ([B, G+1, ...] views), so the hook must be
    # shape-polymorphic over leading dims (the trainer's hooks are). The
    # draft proposes from its own unadjusted distribution; the acceptance
    # rule corrects it, so sampling stays exact w.r.t. the ADJUSTED target
    # — a mismatched draft just lowers the acceptance rate.
):
    """Sample ``config.max_new_tokens`` continuations via draft-and-verify.

    ``*_apply(params, input_ids, attention_mask, positions, cache,
    cache_index, **kw)`` follow the model wrappers' ``__call__`` contract;
    the target's outputs must include ``logits`` (+ ``value`` when a value
    head is attached), the draft's just ``logits``. Fully jittable with
    static ``config``/``gamma``.
    """
    B, P = input_ids.shape
    per_row = bool(config.per_row_rng)
    if per_row:
        # Per-row key chains (the continuous-batching composition seam,
        # ROADMAP item 2): every rng consumer below — each round's G draft
        # proposals, the acceptance uniforms, the residual/bonus draw —
        # advances a [B, 2] per-row chain by a FIXED number of
        # split_row_keys steps per round, so a row's sample stream depends
        # only on (its chain start, its round index), never on batch
        # composition. Rounds are batch-synchronized (done rows burn
        # rounds without touching their committed outputs), hence a
        # batched run is BIT-IDENTICAL per row to running that row alone
        # with its chain (tests/test_speculative.py B=1-loop parity).
        # ``rng`` may be one key (chains derived via per_row_keys — the
        # plain sampler's convention) or an already-stacked [B, 2] chain
        # set (the slot engine's convention).
        rng = per_row_keys(rng, B) if jnp.asarray(rng).ndim == 1 else rng
    N = config.max_new_tokens
    G = gamma
    NB = N + G + 1  # token buffer padded so block writes never clip
    S = P + N + G  # cache slots: commits cap at P+N, probes run G past c-1
    V_pad = config.pad_token_id
    input_ids = input_ids.astype(jnp.int32)
    prompt_mask = attention_mask.astype(jnp.int32)

    t_cache = init_target_cache(B, S)
    d_cache = init_draft_cache(B, S)

    # ---- prefill both caches over the prompt block ----
    slot0 = jnp.concatenate([prompt_mask, jnp.zeros((B, NB - 1), jnp.int32)], axis=1)
    t_pre = target_apply(
        target_params, input_ids, attention_mask=slot0, positions=None,
        cache=t_cache, cache_index=jnp.asarray(0, jnp.int32), logits_span=(P - 1, P),
    )
    d_pre = draft_apply(
        draft_params, input_ids, attention_mask=slot0, positions=None,
        cache=d_cache, cache_index=jnp.asarray(0, jnp.int32), logits_span=(P - 1, P),
    )

    def round_step(carry):
        # the shared round (also the CB spec segment's body) — one function,
        # bit-identity by construction
        return spec_round_step(
            carry,
            prompt_mask=prompt_mask,
            target_apply=target_apply,
            target_params=target_params,
            draft_apply=draft_apply,
            draft_params=draft_params,
            config=config,
            G=G,
            transition_mask=transition_mask,
            adjust_logits=adjust_logits,
        )

    def cond(carry):
        return ~jnp.all(carry["done"])

    init = {
        "rng": rng,
        "n_out": jnp.zeros((B,), jnp.int32),
        "done": jnp.zeros((B,), bool),
        "t_last": input_ids[:, -1],
        "t_cache": t_pre["cache"],
        "d_cache": d_pre["cache"],
        "tokens": jnp.full((B, NB), V_pad, jnp.int32),
        "logprobs": jnp.zeros((B, NB), jnp.float32),
        "values": jnp.zeros((B, NB), jnp.float32),
        "mask": jnp.zeros((B, NB), jnp.int32),
        "rounds": jnp.asarray(0, jnp.int32),
        "accepted": jnp.asarray(0, jnp.int32),
        "live_rounds": jnp.asarray(0, jnp.int32),
        "committed": jnp.asarray(0, jnp.int32),
    }
    final = jax.lax.while_loop(cond, round_step, init)

    tokens = final["tokens"][:, :N]
    sequences = jnp.concatenate([input_ids, tokens], axis=1)
    out = GenerationOutput(
        sequences=sequences,
        response_tokens=tokens,
        response_mask=final["mask"][:, :N],
        response_logprobs=final["logprobs"][:, :N],
        response_values=final["values"][:, :N],
        prompt_mask=prompt_mask,
    )
    if return_stats:
        stats = {
            "rounds": final["rounds"],
            "accepted_draft_tokens": final["accepted"],
            # fraction of proposed draft tokens accepted (per live row-round)
            "acceptance_rate": final["accepted"]
            / jnp.maximum(final["live_rounds"] * G, 1),
            # committed tokens per live row-round (throughput multiplier,
            # ∈ [1, G+1] — every live round commits at least the residual)
            "tokens_per_round": final["committed"]
            / jnp.maximum(final["live_rounds"], 1),
        }
        return out, stats
    return out
