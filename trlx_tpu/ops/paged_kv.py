"""Paged KV cache: fixed-size KV blocks + per-slot block tables (L0).

The dense per-slot caches (``ops/sampling.py::generate``,
``ops/slot_refill.py``) allocate ``[B, S = P + N]`` KV rows up front — an
HBM ceiling of ``slots × max_length`` that is mostly dead space whenever
responses end early or prompts share prefixes. Here the persistent KV state
is a **block pool**: ``max_blocks`` fixed-size blocks of ``block_size``
slots each, plus a per-slot **block table** mapping logical cache columns
``s`` to pool rows ``table[b, s // block_size]``. Blocks are allocated as
sequences actually grow (host allocator, ``trlx_tpu/engine/allocator.py``)
and freed at harvest, so the pool's high-water tracks *live tokens*; shared
prompt prefixes point several tables at one refcounted block
(``trlx_tpu/engine/prefix_cache.py``) — the vLLM PagedAttention layout
(Kwon et al. 2023), rebuilt functionally for jitted JAX programs.

Bit-parity strategy (pinned by ``tests/test_engine.py``): attention never
learns about blocks. Each compiled program **gathers** the pool through the
table into the exact dense ``[rows, S, kvH, D]`` view the model already
consumes, runs the *unchanged* dense compute (prefill / slot-refill decode
segment), and **scatters** the newly written span back into the pool. The
gathered view is bit-identical to the dense backend's cache in every
attention-visible position (committed blocks reproduce committed values;
unallocated table entries point at the reserved all-zeros block 0; recycled
blocks may hold stale values only at slot-masked positions, where the
``-1e9`` bias underflows softmax to exactly ``0.0`` — a zero contribution,
same as the dense cache's zeros). Hence paged decode is bit-identical to
dense slot-refill decode, which is bit-identical to plain ``generate``
under per-row RNG.

The dense view is a per-program *temporary* (alive only inside one XLA
program); the pool + table are the persistent state. The Pallas
paged-attention decode kernel that reads blocks in place — removing the
transient view from the decode inner loop — lives in
``ops/paged_attention.py`` (selected by ``engine.decode_kernel: pallas``);
the gather path here stays as the bit-equivalence reference it must
reproduce, and remains the only prefill path.

Pool layout reuses the model cache structure verbatim:
``init_cache_fn(max_blocks, block_size)`` — the block axis rides the cache's
batch axis, ``block_size`` its length axis. Unscanned leaves are
``[NB, bs, kvH, D]`` (per-layer list of ``{"k","v"}``), scanned leaves
``[L, NB, bs, kvH, D]``; the layout test is ``leaf.ndim - 4`` exactly as in
``ops/slot_refill.py``.
"""

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "ZERO_BLOCK",
    "PagedKV",
    "PagedSpec",
    "num_table_blocks",
    "init_paged_kv",
    "gather_view",
    "scatter_span",
    "scatter_steps",
    "attach_block_table",
    "detach_block_table",
    "kv_bytes",
    "block_bytes",
    "dense_kv_bytes",
]

# Physical block 0 is reserved as the permanent all-zeros block: fresh table
# entries point here, so gathering an unallocated region reproduces the
# dense cache's zeros. The allocator never hands it out and no scatter ever
# targets it (valid writes always go through allocated table entries;
# padding/invalid lanes use an out-of-range id and scatter-drop).
ZERO_BLOCK = 0


class PagedSpec(NamedTuple):
    """Static paged-cache geometry (compile-time constants)."""

    block_size: int
    max_blocks: int  # pool rows, including the reserved zero block


class PagedKV(NamedTuple):
    """The persistent paged KV state threaded through engine programs.

    ``pool`` is a model-cache pytree over ``(max_blocks, block_size)``;
    ``block_table`` is ``[B, TB]`` int32 of physical block ids (host-managed
    between segments; pure data inside compiled programs)."""

    pool: Any
    block_table: jax.Array


def num_table_blocks(slots: int, block_size: int) -> int:
    """Table width: blocks needed to cover ``slots`` logical columns."""
    return -(-slots // block_size)


def init_paged_kv(
    init_cache_fn, spec: PagedSpec, batch_size: int, slots: int
) -> PagedKV:
    """All-zeros pool + all-zero-block tables for ``batch_size`` slots."""
    return PagedKV(
        pool=init_cache_fn(spec.max_blocks, spec.block_size),
        block_table=jnp.zeros(
            (batch_size, num_table_blocks(slots, spec.block_size)), jnp.int32
        ),
    )


def _scanned(leaf: jax.Array) -> bool:
    # pool/cache leaves: [NB, bs, kvH, D] per layer, or [L, NB, bs, kvH, D]
    # when cfg.scan_layers stacked the layer axis in front
    return leaf.ndim - 4 == 1


def gather_view(pool: Any, block_table: jax.Array, slots: int) -> Any:
    """Dense ``[rows, slots, kvH, D]`` cache view of ``block_table``'s rows —
    the exact pytree the model's decode/prefill forwards consume. Table ids
    are clamp-gathered (jnp default), so out-of-range padding ids read the
    last pool row; such lanes are never attention-visible (their slot mask
    is 0) and never scattered back (drop-mode writes)."""
    R, TB = block_table.shape

    def leaf_view(leaf):
        if leaf is None:
            return None
        bs = leaf.shape[-3]
        if _scanned(leaf):
            v = leaf[:, block_table]  # [L, R, TB, bs, kvH, D]
            v = v.reshape(v.shape[:1] + (R, TB * bs) + v.shape[4:])
            return v[:, :, :slots]
        v = leaf[block_table]  # [R, TB, bs, kvH, D]
        v = v.reshape((R, TB * bs) + v.shape[3:])
        return v[:, :slots]

    return jax.tree_util.tree_map(leaf_view, pool, is_leaf=lambda x: x is None)


def scatter_span(
    pool: Any,
    block_table: jax.Array,  # [R, TB] — rows being written
    dense_rows: Any,  # dense cache view [R, >= start+length, kvH, D]
    start: int,
    length: int,
) -> Any:
    """Commit slots ``[start, start + length)`` of a dense row view into the
    pool (the prefill write-back). Static span; drop-mode scatter, so
    padding rows (tables full of an out-of-range id) write nothing."""
    if length <= 0:
        return pool
    R, TB = block_table.shape
    cols = start + jnp.arange(length)  # [length]

    def leaf_scatter(pool_leaf, view_leaf):
        if pool_leaf is None:
            return None
        blk_size = pool_leaf.shape[-3]
        blk = block_table[:, cols // blk_size]  # [R, length]
        off = jnp.broadcast_to((cols % blk_size)[None, :], (R, length))
        if _scanned(pool_leaf):
            vals = view_leaf[:, :, start : start + length]
            return pool_leaf.at[:, blk, off].set(
                vals.astype(pool_leaf.dtype), mode="drop"
            )
        vals = view_leaf[:, start : start + length]
        return pool_leaf.at[blk, off].set(vals.astype(pool_leaf.dtype), mode="drop")

    return jax.tree_util.tree_map(
        leaf_scatter, pool, dense_rows, is_leaf=lambda x: x is None
    )


def scatter_steps(
    pool: Any,
    block_table: jax.Array,  # [B, TB]
    dense_view: Any,  # post-segment dense cache view [B, S, kvH, D]
    base_cols: jax.Array,  # [B] first written column per row (P + step before)
    counts: jax.Array,  # [B] columns actually written (step advance)
    max_steps: int,  # static bound: the segment length
) -> Any:
    """Commit each row's decode-segment writes — columns
    ``[base_cols[b], base_cols[b] + counts[b])`` — back into the pool.
    Rows that froze mid-segment commit only their live writes; the dense
    backend's harmless dead writes (done rows re-writing masked columns)
    are simply not carried over, which is equivalent under the slot mask."""
    B, TB = block_table.shape
    j = jnp.arange(max_steps)[None, :]  # [1, max_steps]
    cols = base_cols[:, None] + j  # [B, max_steps]
    valid = j < counts[:, None]

    def leaf_scatter(pool_leaf, view_leaf):
        if pool_leaf is None:
            return None
        blk_size = pool_leaf.shape[-3]
        S = view_leaf.shape[-3]
        cols_safe = jnp.minimum(cols, S - 1)
        blk = jnp.take_along_axis(block_table, cols_safe // blk_size, axis=1)
        blk = jnp.where(valid, blk, pool_leaf.shape[-4])  # invalid → drop
        off = cols_safe % blk_size
        if _scanned(pool_leaf):
            vals = jax.vmap(lambda row, c: row[:, c], in_axes=(1, 0), out_axes=1)(
                view_leaf, cols_safe
            )  # [L, B, max_steps, kvH, D]
            return pool_leaf.at[:, blk, off].set(
                vals.astype(pool_leaf.dtype), mode="drop"
            )
        vals = jax.vmap(lambda row, c: row[c])(view_leaf, cols_safe)
        return pool_leaf.at[blk, off].set(vals.astype(pool_leaf.dtype), mode="drop")

    return jax.tree_util.tree_map(
        leaf_scatter, pool, dense_view, is_leaf=lambda x: x is None
    )


def attach_block_table(pool: Any, block_table: jax.Array) -> Any:
    """Per-layer model-cache views of the pool that CARRY the block table —
    the cache pytree the kernel decode path feeds ``apply_fn``. The model's
    attention (``models/transformer.py::Attention``) recognises the
    ``"block_table"`` leaf and reads/writes K/V through the table in place
    (``ops/paged_attention.py``) instead of expecting a dense view.

    Rows whose table entries are out of range (``>= max_blocks`` — frozen
    slots the decode loop poisons, bucket-padding refill rows) write
    nothing (drop-mode) and read clamped garbage their callers discard.
    """
    if isinstance(pool, list):  # per-layer [{"k", "v"}, ...]
        return [
            None if layer is None else {**layer, "block_table": block_table}
            for layer in pool
        ]
    # scanned layout {"k": [L, NB, bs, KV, D], ...}: nn.scan slices every
    # cache leaf along the layer axis, so the (tiny, int32) table is tiled
    L = pool["k"].shape[0]
    return {
        **pool,
        "block_table": jnp.broadcast_to(
            block_table[None], (L,) + block_table.shape
        ),
    }


def detach_block_table(cache: Any) -> Any:
    """Inverse of :func:`attach_block_table`: strip the table leaves, give
    back the bare pool pytree (what ``PagedKV.pool`` persists)."""
    if isinstance(cache, list):
        return [
            None
            if layer is None
            else {k: v for k, v in layer.items() if k != "block_table"}
            for layer in cache
        ]
    return {k: v for k, v in cache.items() if k != "block_table"}


def kv_bytes(cache: Any) -> int:
    """Total bytes of a KV pytree (dense cache, pool, or PagedKV pool) —
    the persistent-allocation number behind ``memory/kv_cache_bytes``."""
    if isinstance(cache, PagedKV):
        cache = cache.pool
    return int(
        sum(
            int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree_util.tree_leaves(cache)
        )
    )


def block_bytes(cache: Any) -> int:
    """Bytes of ONE block across all layers/k/v — multiply by
    blocks-in-use for the live-token-scaled high-water number."""
    if isinstance(cache, PagedKV):
        cache = cache.pool
    total = 0
    for leaf in jax.tree_util.tree_leaves(cache):
        nb = leaf.shape[-4]
        total += int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize // nb
    return int(total)


def dense_kv_bytes(cfg: Any, batch_size: int, slots: int) -> int:
    """Analytic dense-cache bytes for a model config — the serial sampler
    allocates its cache inside the jitted program, so the gauge is computed
    rather than measured (exact: shapes are static)."""
    itemsize = np.dtype(cfg.dtype).itemsize
    return int(
        2 * cfg.num_layers * batch_size * slots * cfg.kv_heads
        * cfg.dims_per_head * itemsize
    )
