"""Slot-refill decode: the device half of continuous-batching rollouts.

The plain sampler (``ops/sampling.py::generate``) runs a whole ``[B]`` batch
until the *longest* row finishes — every early-EOS row burns decode steps as
padding, and nothing reaches the host until the chunk drains. Here decode is
restructured into fixed-size **segments** over per-slot state: one compiled
program with static shapes, reused across segments. After each segment the
host harvests finished slots and refills them with fresh prompts via an
on-demand prefill into the freed KV-cache rows, so the device batch stays
full while the prompt queue lasts (PipelineRL, arXiv:2509.19128; OPPO,
arXiv:2509.25762).

Bit-parity contract (pinned by ``tests/test_continuous_batching.py``): under
per-row RNG (``GenerationConfig.per_row_rng``) every sequence's tokens /
logprobs / values / mask are **bit-identical** to what plain ``generate``
produces for that prompt at the same padded prompt width and batch size.
The ingredients:

- per-row key chains (``sampling.per_row_keys`` / ``split_row_keys``): a
  row's sample stream depends only on (its key, its step), never on batch
  composition or slot position;
- per-slot ``cache_index`` vectors (the machinery the speculative path
  already drove through ``models/transformer.py::Attention``): slots decode
  at different depths inside one forward;
- the refill is gather-prefill-scatter: only the ``R`` fresh prompts run a
  prefill forward (same structure as plain ``generate``'s prefill —
  ``logits_span=(P-1, P)``, slot-mask attention — at power-of-two bucket
  batch sizes), then scatter into the freed slots with drop-mode indexing.
  Total refill cost over a collection is the serial path's prefill cost
  (every prompt prefills exactly once), NOT a full-batch forward per refill
  event. Rows are row-independent in every dense op, so a row's prefill
  output is bit-identical across batch sizes (pinned by the parity tests);
- finished slots freeze (no buffer/step/rng writes), so harvested rows are
  exactly what the plain loop would have produced, and refilling later
  cannot disturb them.

Cache backends: the decode/refill programs are generic over where the KV
actually lives. The default (dense) backend keeps the historical per-slot
``[B, S]`` cache byte-for-byte. With ``paged=PagedSpec(...)`` the
persistent state is a block pool + per-slot block tables
(``ops/paged_kv.py``): each program gathers the pool into the exact dense
view the model consumes, runs the *unchanged* dense compute, and scatters
the written span back — so paged decode is bit-identical to dense decode
by construction (``tests/test_engine.py``). With
``decode_kernel="pallas"`` the paged *decode segments* skip the gather
entirely: the in-place Pallas paged-attention kernel + fused sampling
(``ops/paged_attention.py``) read and write K/V through the block table,
bit-identical to the gather path (``tests/test_paged_attention.py``). The paged refill additionally
supports a static ``hit`` offset: rows whose leading ``hit`` cache columns
are already committed (prefix-cache hits, ``trlx_tpu/engine/``) prefill
only their unshared suffix ``[hit, P)`` — the suffix forward attends to
the shared blocks through the gathered view, reproducing the full
prefill's values bit-for-bit.

Host-side orchestration (queue, harvest order, block allocation, stats)
lives in ``trlx_tpu/engine/core.py`` (re-exported for compatibility from
``trlx_tpu/pipeline/continuous_batching.py``).
"""

import dataclasses
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.ops.paged_kv import (
    PagedKV,
    PagedSpec,
    attach_block_table,
    detach_block_table,
    gather_view,
    init_paged_kv,
    scatter_span,
    scatter_steps,
)
from trlx_tpu.ops.sampling import (
    GenerationConfig,
    last_step_info,
    sample_token_from_logits,
    split_row_keys,
)
from trlx_tpu.ops.speculative import spec_round_step

__all__ = ["SlotState", "SpecState", "SlotRefillFns", "make_slot_refill_fns"]


class SlotState(NamedTuple):
    """Per-slot decode state threaded through refill/segment programs.

    ``B`` slots over a ``[B, S = P + N]`` KV cache; all leaves static-shaped
    so one compiled segment program serves the whole collection."""

    tokens: jax.Array  # [B, N] response tokens (pad after eos)
    logprobs: jax.Array  # [B, N] behavior logprobs
    values: jax.Array  # [B, N] value-head outputs (0 if no head)
    mask: jax.Array  # [B, N] 1 on real response tokens (incl. eos)
    slot_mask: jax.Array  # [B, S] attention slot mask over the cache
    cache: Any  # KV cache pytree ([B, S, ...] or scanned [L, B, S, ...])
    logits: jax.Array  # [B, V] logits feeding the next sample
    step_out: Any  # last-position model-output views (adjust_logits hook)
    prompt_len: jax.Array  # [B] real (unpadded) prompt lengths
    done: jax.Array  # [B] finished (or empty) slots — frozen in decode
    step: jax.Array  # [B] per-slot decode step
    rng: jax.Array  # [B, 2] per-slot key chains


class SpecState(NamedTuple):
    """Per-slot state of the *speculative* decode segments
    (``speculative=k`` in :func:`make_slot_refill_fns`).

    The shape geometry is solo ``generate_speculative``'s, per slot: token
    buffers are ``[B, NB = N + G + 1]`` (a round's commit block never
    clips), the target cache spans ``S = P + N + G`` slots *per row*
    through the paged block table, and the draft keeps its own small dense
    ``[B, S]`` cache right in the state (the draft has no prefix sharing —
    paging it would buy nothing and cost a gather per proposal). Field
    names shared with :class:`SlotState` (``tokens``/``logprobs``/
    ``values``/``mask``/``done``/``step``/``cache``/``rng``/``prompt_len``)
    keep the host engine's harvest/refill bookkeeping backend-agnostic;
    ``step`` counts COMMITTED tokens (rows advance unevenly — the engine
    reads it per row instead of assuming uniform segment advancement)."""

    tokens: jax.Array  # [B, NB] response tokens (pad after eos)
    logprobs: jax.Array  # [B, NB] behavior logprobs (target's)
    values: jax.Array  # [B, NB] value-head outputs (0 if no head)
    mask: jax.Array  # [B, NB] 1 on real response tokens (incl. eos)
    prompt_mask: jax.Array  # [B, P] the rows' prompt masks (round masks
    # are rebuilt from this + step every round, like solo)
    cache: Any  # target PagedKV: block pool + per-slot tables, S columns
    d_cache: Any  # draft dense KV cache pytree ([B, S, ...] / scanned)
    t_last: jax.Array  # [B] last committed token (re-fed every round)
    prompt_len: jax.Array  # [B] real (unpadded) prompt lengths
    done: jax.Array  # [B] finished (or empty) slots — frozen
    step: jax.Array  # [B] committed generated tokens (solo's n_out)
    rng: jax.Array  # [B, 2] per-slot key chains
    # cumulative acceptance accounting (absolute counters — the engine
    # differences them across segments for its gauges)
    rounds: jax.Array  # [] spec rounds run
    accepted: jax.Array  # [] accepted draft tokens (pre-truncation)
    live_rounds: jax.Array  # [] live row-rounds
    committed: jax.Array  # [] committed tokens (post budget/eos clip)


class SlotRefillFns(NamedTuple):
    """The compiled slot-refill programs + static shape info."""

    init_state: Callable[[], SlotState]  # fresh all-empty state (host-cheap)
    # (params, state, ids [r,P], mask [r,P], slot_idx [r], keys [r,2]
    #  [, table_rows [r,TB], hit]) — host wrapper that pads r to a
    # power-of-two bucket and dispatches the cached compiled program for
    # that (bucket, hit) pair
    refill_rows: Callable[..., SlotState]
    refill_program: Callable[..., Callable]  # (bucket[, hit]) → compiled fn
    prewarm: Callable[[Any, SlotState], SlotState]  # once-per-fns bucket warmup
    decode_segment: Callable[..., Tuple[SlotState, jax.Array, jax.Array]]
    batch_size: int
    prompt_len: int  # padded prompt width P (fixed per engine)
    max_new_tokens: int
    segment_len: int = 8  # decode steps per compiled segment
    paged: Optional[PagedSpec] = None  # None = dense per-slot cache
    decode_kernel: str = "xla"  # "pallas" = in-place paged decode kernel
    prefill_kernel: str = "xla"  # "pallas" = in-place paged prefill kernel
    # chunked-prefill programs (paged only): prefill a mid-prompt span
    # [start, end) with end < P — cache-only, no SlotState row scatter
    # (the final span [start, P) is the ordinary refill program)
    prefill_chunk_rows: Optional[Callable[..., SlotState]] = None
    prefill_chunk_program: Optional[Callable[..., Callable]] = None
    # speculative decode segments (0 = plain): each segment runs up to
    # ``segment_len`` draft-propose/verify/accept ROUNDS, committing up to
    # ``speculative + 1`` tokens per live row per round. The programs then
    # take ``params = (target_params, draft_params)``.
    speculative: int = 0


def _row_where(flag: jax.Array, new: Any, old: Any) -> Any:
    """Masked per-row merge for a pytree of ``[B, ...]`` leaves (batch axis
    first). Scalar/None leaves pass through untouched."""
    B = flag.shape[0]

    def merge(n, o):
        if n is None or not hasattr(n, "ndim") or n.ndim == 0:
            return n
        return jnp.where(flag.reshape((B,) + (1,) * (n.ndim - 1)), n, o)

    return jax.tree_util.tree_map(merge, new, old, is_leaf=lambda x: x is None)


def _row_set(buf: jax.Array, val: jax.Array, col: jax.Array, live: jax.Array) -> jax.Array:
    """Write ``val[i]`` into ``buf[i, col[i]]`` for live rows; frozen rows
    keep their buffer untouched (a finished-but-unharvested slot must never
    be clobbered by clamped out-of-range writes)."""
    written = jax.vmap(
        lambda row, v, c: jax.lax.dynamic_update_slice(row, v[None], (c,))
    )(buf, val.astype(buf.dtype), col)
    return jnp.where(live[:, None], written, buf)


def make_slot_refill_fns(
    apply_fn: Callable[..., Dict[str, Any]],
    init_cache_fn: Callable[[int, int], Any],
    batch_size: int,
    prompt_len: int,
    config: GenerationConfig,
    adjust_logits: Optional[Callable[[Dict[str, Any], jax.Array], jax.Array]] = None,
    segment_len: int = 8,
    params_example: Any = None,
    jit: bool = True,
    paged: Optional[PagedSpec] = None,
    decode_kernel: str = "xla",
    prefill_kernel: str = "xla",
    speculative: int = 0,
    draft_apply: Optional[Callable[..., Dict[str, Any]]] = None,
    init_draft_cache_fn: Optional[Callable[[int, int], Any]] = None,
    transition_mask: Optional[jax.Array] = None,
) -> SlotRefillFns:
    """Build the (jitted) slot-refill programs for one shape bucket.

    ``apply_fn(params, input_ids, attention_mask, positions, cache,
    cache_index, ...)`` is the model wrappers' ``__call__``;
    ``params_example`` (real params or ShapeDtypeStructs) is needed once to
    shape the ``step_out`` carry of the empty state via ``eval_shape`` —
    nothing is executed. ``config.per_row_rng`` must be True: slot migration
    is only stream-invariant under per-row key chains.

    ``paged`` switches the KV backend to a block pool + per-slot block
    tables (``ops/paged_kv.py``); the refill and segment programs then take
    their block-table rows from the host allocator (``trlx_tpu/engine/``)
    and gather/scatter around the unchanged dense compute.

    ``decode_kernel`` selects the paged *decode-segment* compute
    (``engine.decode_kernel``): ``"xla"`` is the gather → dense compute →
    scatter reference; ``"pallas"`` runs the in-place paged-attention
    decode kernel + fused sampling (``ops/paged_attention.py``) — K/V read
    and written through the block table with no transient dense view.
    Bit-identical to the gather path by contract
    (``tests/test_paged_attention.py``).

    ``prefill_kernel`` selects the paged *refill prefill* compute
    (``engine.prefill_kernel``): ``"xla"`` is the gather → dense prefill →
    scatter reference; ``"pallas"`` runs the in-place paged-prefill kernel
    (``ops/paged_prefill.py`` via ``models/transformer.py``) — the chunk's
    K/V committed through the block table with no dense view on entry and
    no scatter on exit, bit-identical to the gather path by contract.
    With it (or without — the chunk programs exist for both flavors), the
    ``prefill_chunk_rows`` programs prefill a mid-prompt span
    ``[start, end)``, ``end < P``, committing K/V only: the host engine
    interleaves these with decode segments (``engine.prefill_chunk``) so a
    long prompt never stalls live decode slots longer than one chunk.

    ``speculative = k > 0`` (``engine.speculative``) swaps the decode
    segment for the *speculative* segment: each segment runs up to
    ``segment_len`` draft-propose → verify → accept ROUNDS of
    :func:`trlx_tpu.ops.speculative.spec_round_step` — literally the solo
    sampler's round body, so every slot's token stream is bit-identical to
    a solo ``generate_speculative`` run with that row's key chain,
    regardless of batch composition or refills. Requires the paged backend
    (the verify writes flow through the block table with drop-mode
    commits), per-row RNG, plus ``draft_apply`` / ``init_draft_cache_fn``
    for the proposal model. Both kernel flavors compose: ``decode_kernel:
    pallas`` runs the rounds in place — each verify forward commits its
    ``G + 1`` probe columns through per-row (done-poisoned) block tables
    and reads K/V via the multi-position verify kernel
    (``ops/paged_attention.py::paged_verify_attention``) — while ``xla``
    keeps the gather → rounds → scatter reference shape. ``transition_mask``
    (the trainer's logit mask) must be passed HERE rather than composed
    into ``adjust_logits``: the rounds apply it to draft proposals and
    target verify distributions separately, exactly like solo.
    ``params`` for every program becomes ``(target_params, draft_params)``
    — one tuple, so mid-stream ``swap_params`` swaps both atomically.
    """
    if decode_kernel not in ("xla", "pallas"):
        raise ValueError(
            f"unknown decode_kernel '{decode_kernel}' (xla | pallas)"
        )
    if decode_kernel == "pallas" and paged is None:
        raise ValueError(
            "decode_kernel: pallas is the in-place *paged* decode kernel — "
            "it requires the paged KV backend (engine.backend: paged)"
        )
    if prefill_kernel not in ("xla", "pallas"):
        raise ValueError(
            f"unknown prefill_kernel '{prefill_kernel}' (xla | pallas)"
        )
    if prefill_kernel == "pallas" and paged is None:
        raise ValueError(
            "prefill_kernel: pallas is the in-place *paged* prefill kernel "
            "(ops/paged_prefill.py) — it requires the paged KV backend "
            "(engine.backend: paged)"
        )
    G = int(speculative or 0)
    if G < 0:
        raise ValueError(f"speculative must be >= 0, got {G}")
    if G:
        if paged is None:
            raise ValueError(
                "speculative decode segments require the paged KV backend "
                "(engine.backend: paged) — the verify pass commits accepted "
                "K/V through the block table with drop-mode writes"
            )
        if draft_apply is None or init_draft_cache_fn is None:
            raise ValueError(
                "speculative decode segments need the draft model: pass "
                "draft_apply and init_draft_cache_fn "
                "(model.draft_model_path resolves them in the trainer)"
            )
        if not config.per_row_rng:
            raise ValueError(
                "engine.speculative requires per-row RNG chains "
                "(GenerationConfig.per_row_rng=True): speculative slot "
                "streams are only batch-composition-invariant when draft "
                "proposals, acceptance uniforms, and residual/bonus draws "
                "advance [B, 2] per-row key chains"
            )
    if not config.per_row_rng:
        config = dataclasses.replace(config, per_row_rng=True)
    B, P, N = batch_size, prompt_len, config.max_new_tokens
    # speculative geometry is solo's: commits cap at P+N but each round
    # probes G slots past the last commit, and the key width must match
    # solo's exactly (see spec_round_step / _make_prefill_chunk's
    # key-width lowering note) — G = 0 reduces to the plain S = P + N
    S = P + N + G
    NB = N + G + 1  # spec token buffers: block writes never clip

    def empty_state() -> SlotState:
        # step_out structure comes from an abstract prefill — shapes only
        # (the dense [B, S] cache inside eval_shape never materializes,
        # which matters for the paged backend: its persistent state is the
        # block pool, not a dense cache)
        out_sds = jax.eval_shape(
            lambda p: apply_fn(
                p,
                jnp.zeros((B, P), jnp.int32),
                attention_mask=jnp.zeros((B, S), jnp.int32),
                positions=None,
                cache=init_cache_fn(B, S),
                cache_index=jnp.asarray(0, jnp.int32),
                logits_span=(P - 1, P),
            ),
            params_example,
        )
        step_out = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape[:1] + s.shape[2:], s.dtype),
            last_step_info_abstract(out_sds),
        )
        step_out["last_tokens"] = jnp.zeros((B,), jnp.int32)
        logits_sds = out_sds["logits"]
        cache = (
            init_paged_kv(init_cache_fn, paged, B, S)
            if paged is not None
            else init_cache_fn(B, S)
        )
        return SlotState(
            tokens=jnp.full((B, N), config.pad_token_id, jnp.int32),
            logprobs=jnp.zeros((B, N), jnp.float32),
            values=jnp.zeros((B, N), jnp.float32),
            mask=jnp.zeros((B, N), jnp.int32),
            slot_mask=jnp.zeros((B, S), jnp.int32),
            cache=cache,
            # native model dtype: plain generate carries raw logits, and the
            # adjust-logits hook must see identical bits in both samplers
            logits=jnp.zeros((B, logits_sds.shape[-1]), logits_sds.dtype),
            step_out=step_out,
            prompt_len=jnp.zeros((B,), jnp.int32),
            done=jnp.ones((B,), bool),  # empty slots never decode
            step=jnp.zeros((B,), jnp.int32),
            rng=jnp.zeros((B, 2), jnp.uint32),
        )

    def empty_spec_state() -> SpecState:
        # no eval_shape needed: spec segments carry no logits/step_out —
        # every round re-derives both models' distributions by re-feeding
        # the last committed token, exactly like solo
        return SpecState(
            tokens=jnp.full((B, NB), config.pad_token_id, jnp.int32),
            logprobs=jnp.zeros((B, NB), jnp.float32),
            values=jnp.zeros((B, NB), jnp.float32),
            mask=jnp.zeros((B, NB), jnp.int32),
            prompt_mask=jnp.zeros((B, P), jnp.int32),
            cache=init_paged_kv(init_cache_fn, paged, B, S),
            d_cache=init_draft_cache_fn(B, S),
            t_last=jnp.zeros((B,), jnp.int32),
            prompt_len=jnp.zeros((B,), jnp.int32),
            done=jnp.ones((B,), bool),  # empty slots never decode
            step=jnp.zeros((B,), jnp.int32),
            rng=jnp.zeros((B, 2), jnp.uint32),
            rounds=jnp.asarray(0, jnp.int32),
            accepted=jnp.asarray(0, jnp.int32),
            live_rounds=jnp.asarray(0, jnp.int32),
            committed=jnp.asarray(0, jnp.int32),
        )

    def last_step_info_abstract(out_sds: Dict[str, Any]) -> Dict[str, Any]:
        # eval_shape twin of sampling.last_step_info (keeps [B, 1, ...] dims
        # so the zeros() above can drop the per-step axis uniformly)
        from trlx_tpu.ops.sampling import _NON_CARRY_KEYS

        return {
            k: v
            for k, v in out_sds.items()
            if k not in _NON_CARRY_KEYS and v is not None
        }

    def _make_refill(R: int, hit: int = 0):
        def refill(
            params: Any,
            state: SlotState,
            input_ids: jax.Array,  # [R, P] left-padded fresh prompts
            prompt_mask: jax.Array,  # [R, P]
            slot_idx: jax.Array,  # [R] target slots; >= B = padding (dropped)
            new_keys: jax.Array,  # [R, 2] per-row key chains
            table_rows: Optional[jax.Array] = None,  # [R, TB] (paged only)
        ) -> SlotState:
            """Gather-prefill-scatter into freed cache slots: only the ``R``
            refilled rows run the prefill forward (cost ``R·(P − hit)``
            tokens — the serial path's prefill cost amortized over the run,
            minus prefix-cache hits — instead of a full ``B·P`` forward per
            refill event), then scatter into the big state at ``slot_idx``.
            Out-of-range indices (the power-of-two bucket padding) drop:
            every lane write is deterministic, no duplicate-index races.

            With the paged backend and ``hit > 0`` the leading ``hit`` cache
            columns are already committed in shared blocks: only the suffix
            ``[hit, P)`` runs the forward, attending to the shared prefix
            through the gathered dense view — per-query-row independence of
            every dense op makes the suffix's KV/logits bit-identical to a
            full prefill's (the same property the bucket-size invariance
            already relies on)."""
            input_ids = input_ids.astype(jnp.int32)
            prompt_mask = prompt_mask.astype(jnp.int32)
            slot_mask_r = jnp.concatenate(
                [prompt_mask, jnp.zeros((R, N), jnp.int32)], axis=1
            )
            if paged is not None and prefill_kernel == "pallas":
                # in-place paged prefill (ops/paged_prefill.py via the
                # model's paged branch): the suffix's K/V commits through
                # the table and attention reads pool blocks straight into
                # VMEM — no dense view exists, before or after. Committed
                # prefix blocks (hit > 0, or earlier prefill chunks) are
                # read in place; everything else is bias-masked to an
                # exact-0.0 softmax contribution.
                row_cache = attach_block_table(state.cache.pool, table_rows)
            elif paged is not None and hit > 0:
                # dense view of the refilled rows: shared prefix blocks hold
                # committed values; everything else reads the zero block or
                # recycled slots the mask keeps out of attention (masked
                # scores underflow softmax to exactly 0.0, same as the
                # dense cache's zeros)
                row_cache = gather_view(state.cache.pool, table_rows, S)
            else:
                # cold refill (dense, or paged with no committed prefix):
                # the forward writes every prompt column itself and the
                # response region is masked — a zero cache is equivalent
                # and skips the pool gather entirely
                row_cache = init_cache_fn(R, S)
            out = apply_fn(
                params,
                input_ids[:, hit:],
                attention_mask=slot_mask_r,
                positions=None,
                cache=row_cache,
                cache_index=jnp.asarray(hit, jnp.int32),
                logits_span=(P - hit - 1, P - hit),
            )
            step_out_r = {**last_step_info(out), "last_tokens": input_ids[:, -1]}

            def scat(big, rows):
                if big is None or not hasattr(big, "ndim") or big.ndim == 0:
                    return big
                return big.at[slot_idx].set(rows.astype(big.dtype), mode="drop")

            def scat_cache(big, rows):
                if big.ndim - 4 == 0:
                    return big.at[slot_idx].set(rows.astype(big.dtype), mode="drop")
                # scanned layout [L, B, S, KV, D]: batch axis 1
                return big.at[:, slot_idx].set(rows.astype(big.dtype), mode="drop")

            if paged is not None:
                if prefill_kernel == "pallas":
                    # the forward already committed the span [hit, P) into
                    # the pool through the table (drop-mode writes inside
                    # the model's paged branch) — nothing to scatter
                    new_pool = detach_block_table(out["cache"])
                else:
                    # commit the recomputed span [hit, P) from the dense view
                    new_pool = scatter_span(
                        state.cache.pool, table_rows, out["cache"], hit, P - hit
                    )
                new_cache = PagedKV(
                    pool=new_pool,
                    block_table=state.cache.block_table.at[slot_idx].set(
                        table_rows, mode="drop"
                    ),
                )
            else:
                new_cache = jax.tree_util.tree_map(
                    scat_cache, state.cache, out["cache"]
                )

            tree_scat = lambda big, rows: jax.tree_util.tree_map(  # noqa: E731
                scat, big, rows, is_leaf=lambda x: x is None
            )
            return SlotState(
                tokens=scat(state.tokens, jnp.full((R, N), config.pad_token_id, jnp.int32)),
                logprobs=scat(state.logprobs, jnp.zeros((R, N), jnp.float32)),
                values=scat(state.values, jnp.zeros((R, N), jnp.float32)),
                mask=scat(state.mask, jnp.zeros((R, N), jnp.int32)),
                slot_mask=scat(state.slot_mask, slot_mask_r),
                cache=new_cache,
                logits=scat(state.logits, out["logits"][:, -1, :]),
                step_out=tree_scat(state.step_out, step_out_r),
                prompt_len=scat(state.prompt_len, jnp.sum(prompt_mask, axis=1)),
                done=scat(state.done, jnp.zeros((R,), bool)),
                step=scat(state.step, jnp.zeros((R,), jnp.int32)),
                rng=scat(state.rng, new_keys),
            )

        return refill

    def _make_spec_refill(R: int, hit: int = 0):
        def refill(
            params: Any,  # (target_params, draft_params)
            state: SpecState,
            input_ids: jax.Array,  # [R, P] left-padded fresh prompts
            prompt_mask: jax.Array,  # [R, P]
            slot_idx: jax.Array,  # [R] target slots; >= B = padding (dropped)
            new_keys: jax.Array,  # [R, 2] per-row key chains
            table_rows: Optional[jax.Array] = None,  # [R, TB]
        ) -> SpecState:
            """The speculative twin of ``_make_refill``: prefill the TARGET
            suffix ``[hit, P)`` through the block table exactly like the
            plain paged refill (same forward, same ``scatter_span`` commit
            — K/V only, ``logits_span=(0, 0)``: the first round re-feeds
            the last prompt token, so prefill logits are never consumed),
            plus a full ``[0, P)`` DRAFT prefill on a fresh zero cache
            scattered whole-row into ``state.d_cache`` (the draft shares
            nothing across rows — prefix hits only skip target compute;
            the full-row scatter also zeroes any stale recycled-slot
            columns past ``P``). Both prefills use solo's ``S``-wide slot
            mask, so the refilled row's caches are bit-identical to a solo
            run's post-prefill caches. ``prefill_kernel: pallas`` commits
            the target suffix through the block table in place
            (``ops/paged_prefill.py`` via the model's paged branch) —
            same forward, no gather on entry, no scatter on exit."""
            t_params, d_params = params
            input_ids = input_ids.astype(jnp.int32)
            prompt_mask = prompt_mask.astype(jnp.int32)
            slot_mask_r = jnp.concatenate(
                [prompt_mask, jnp.zeros((R, S - P), jnp.int32)], axis=1
            )
            if prefill_kernel == "pallas":
                row_cache = attach_block_table(state.cache.pool, table_rows)
            elif hit > 0:
                row_cache = gather_view(state.cache.pool, table_rows, S)
            else:
                row_cache = init_cache_fn(R, S)
            t_out = apply_fn(
                t_params,
                input_ids[:, hit:],
                attention_mask=slot_mask_r,
                positions=None,
                cache=row_cache,
                cache_index=jnp.asarray(hit, jnp.int32),
                logits_span=(0, 0),
            )
            if prefill_kernel == "pallas":
                # the forward already committed [hit, P) through the table
                new_pool = detach_block_table(t_out["cache"])
            else:
                new_pool = scatter_span(
                    state.cache.pool, table_rows, t_out["cache"], hit, P - hit
                )
            new_cache = PagedKV(
                pool=new_pool,
                block_table=state.cache.block_table.at[slot_idx].set(
                    table_rows, mode="drop"
                ),
            )
            d_out = draft_apply(
                d_params,
                input_ids,
                attention_mask=slot_mask_r,
                positions=None,
                cache=init_draft_cache_fn(R, S),
                cache_index=jnp.asarray(0, jnp.int32),
                logits_span=(0, 0),
            )

            def scat(big, rows):
                if big is None or not hasattr(big, "ndim") or big.ndim == 0:
                    return big
                return big.at[slot_idx].set(rows.astype(big.dtype), mode="drop")

            def scat_cache(big, rows):
                if big.ndim - 4 == 0:
                    return big.at[slot_idx].set(rows.astype(big.dtype), mode="drop")
                # scanned layout [L, B, S, KV, D]: batch axis 1
                return big.at[:, slot_idx].set(rows.astype(big.dtype), mode="drop")

            return SpecState(
                tokens=scat(
                    state.tokens, jnp.full((R, NB), config.pad_token_id, jnp.int32)
                ),
                logprobs=scat(state.logprobs, jnp.zeros((R, NB), jnp.float32)),
                values=scat(state.values, jnp.zeros((R, NB), jnp.float32)),
                mask=scat(state.mask, jnp.zeros((R, NB), jnp.int32)),
                prompt_mask=scat(state.prompt_mask, prompt_mask),
                cache=new_cache,
                d_cache=jax.tree_util.tree_map(
                    scat_cache, state.d_cache, d_out["cache"]
                ),
                t_last=scat(state.t_last, input_ids[:, -1]),
                prompt_len=scat(state.prompt_len, jnp.sum(prompt_mask, axis=1)),
                done=scat(state.done, jnp.zeros((R,), bool)),
                step=scat(state.step, jnp.zeros((R,), jnp.int32)),
                rng=scat(state.rng, new_keys),
                rounds=state.rounds,
                accepted=state.accepted,
                live_rounds=state.live_rounds,
                committed=state.committed,
            )

        return refill

    _refill_cache: Dict[Tuple[int, int], Callable] = {}
    _warmed = {"done": False}

    def refill_program(bucket: int, hit: int = 0) -> Callable:
        """The compiled refill program for one (power-of-two bucket size,
        prefix-hit offset) pair. ``hit`` is always 0 on the dense backend;
        paged prefix-cache hits compile one extra variant per distinct
        block-aligned hit length, on first use."""
        if (bucket, hit) not in _refill_cache:
            fn = (_make_spec_refill if G else _make_refill)(bucket, hit)
            _refill_cache[(bucket, hit)] = jax.jit(fn) if jit else fn
        return _refill_cache[(bucket, hit)]

    def _make_prefill_chunk(R: int, start: int, end: int):
        def prefill_chunk(
            params: Any,
            state: SlotState,
            input_ids: jax.Array,  # [R, P] left-padded fresh prompts
            prompt_mask: jax.Array,  # [R, P]
            table_rows: jax.Array,  # [R, TB] the rows' block tables
        ) -> SlotState:
            """Prefill the mid-prompt span ``[start, end)`` of ``R`` rows,
            committing K/V into their pool blocks only — no logits, no
            SlotState row scatter (the rows stay empty/done until the final
            span ``[x, P)`` runs the ordinary refill program and seeds the
            sampler). Keys keep the FULL cache width ``S`` with columns
            ``>= end`` masked out: not-yet-prefilled (and response-region)
            columns contribute exact-0.0 softmax terms, and keeping the
            key width identical to the monolithic pass's keeps the score
            dots' shapes identical too — truncating the key axis changes
            the dot's lowering at some shapes (1-ulp contraction drift,
            same genre as the kernel's batch-dim landmine), which would
            break the chunked ≡ unchunked bit-parity the suite pins.
            Tables are taken as an argument (host mirror) — the device
            block-table rows of still-prefilling slots are stale by
            design."""
            # speculative builds chunk only the TARGET's prompt (the draft
            # prefills whole at refill time — it is the small model; only
            # the target's prefill can stall live decode slots)
            t_params = params[0] if G else params
            input_ids = input_ids.astype(jnp.int32)
            prompt_mask = prompt_mask.astype(jnp.int32)
            # visibility: committed prompt columns [0, end) only
            span_mask = prompt_mask * (jnp.arange(P)[None, :] < end)
            key_mask = jnp.concatenate(
                [span_mask, jnp.zeros((R, S - P), jnp.int32)], axis=1
            )
            if prefill_kernel == "pallas":
                row_cache = attach_block_table(state.cache.pool, table_rows)
            elif start > 0:
                row_cache = gather_view(state.cache.pool, table_rows, S)
            else:
                # first chunk: nothing committed below column 0 — a zero
                # cache is equivalent and skips the gather (the cold-refill
                # shortcut)
                row_cache = init_cache_fn(R, S)
            out = apply_fn(
                t_params,
                input_ids[:, start:end],
                attention_mask=key_mask,
                positions=None,
                cache=row_cache,
                cache_index=jnp.asarray(start, jnp.int32),
                logits_span=(0, 0),  # mid-prompt: no sampler to seed
            )
            if prefill_kernel == "pallas":
                pool = detach_block_table(out["cache"])
            else:
                pool = scatter_span(
                    state.cache.pool, table_rows, out["cache"], start,
                    end - start,
                )
            return state._replace(
                cache=PagedKV(pool, state.cache.block_table)
            )

        return prefill_chunk

    _chunk_cache: Dict[Tuple[int, int, int], Callable] = {}

    def prefill_chunk_program(bucket: int, start: int, end: int) -> Callable:
        """The compiled mid-chunk prefill program for one (bucket, span)
        triple. Spans are engine-aligned to absolute multiples of the
        chunk size (plus block-aligned prefix-hit starts), so the variant
        count stays bounded; they compile lazily on first use — their set
        depends on the prompt stream and ``engine.prefill_chunk``."""
        if paged is None:
            raise ValueError(
                "chunked prefill requires the paged KV backend "
                "(engine.backend: paged) — dense per-slot caches have no "
                "span-committing chunk program"
            )
        if not 0 <= start < end < P:
            raise ValueError(
                f"mid-chunk span [{start}, {end}) must sit strictly inside "
                f"the prompt region [0, {P}) — the final span is the "
                "refill program"
            )
        if (bucket, start, end) not in _chunk_cache:
            fn = _make_prefill_chunk(bucket, start, end)
            _chunk_cache[(bucket, start, end)] = jax.jit(fn) if jit else fn
        return _chunk_cache[(bucket, start, end)]

    def prefill_chunk_rows(
        params: Any,
        state: SlotState,
        input_ids: Any,  # [r, P] host or device rows, r <= B
        prompt_mask: Any,
        table_rows: Any,  # [r, TB]
        start: int,
        end: int,
    ) -> SlotState:
        """Host wrapper for one mid-chunk span: the shared bucket+pad
        protocol (``_bucket_pad`` — padding rows carry all-out-of-range
        tables, so their commits drop), then the cached compiled program."""
        bucket, _, input_ids, prompt_mask, table_rows = _bucket_pad(
            input_ids, prompt_mask, table_rows
        )
        return prefill_chunk_program(bucket, start, end)(
            params,
            state,
            jnp.asarray(input_ids),
            jnp.asarray(prompt_mask),
            jnp.asarray(table_rows),
        )

    def prewarm(params: Any, state: SlotState) -> SlotState:
        """Compile every cold (hit = 0) refill bucket with dropped no-op
        calls (all ``slot_idx = B``) so a collection's completion pattern
        never triggers a mid-run XLA compile. Runs ONCE per fns — these
        programs are cached per shape bucket, so later engines over the
        same fns (one per ``make_experience`` call) skip straight through
        instead of re-executing ~2·B·P tokens of dead prefill every
        collection. Prefix-hit variants (paged) compile lazily on first
        hit: their set depends on the prompt stream.

        The no-op results thread through ``state`` (content unchanged —
        every write drops): jit's executable cache keys on input *placement*
        as well as avals, and real refill calls always see computed
        (committed) state leaves. The first bucket runs twice so even it
        gets a committed-state cache entry."""
        if _warmed["done"]:
            return state
        buckets = [1]
        while buckets[-1] < B:
            buckets.append(min(buckets[-1] * 2, B))
        for bucket in [buckets[0]] + buckets:
            args = [
                params,
                state,
                jnp.full((bucket, P), config.pad_token_id, jnp.int32),
                jnp.zeros((bucket, P), jnp.int32),
                jnp.full((bucket,), B, jnp.int32),  # out of range: drop
                jnp.zeros((bucket, 2), jnp.asarray(state.rng).dtype),
            ]
            if paged is not None:
                TB = state.cache.block_table.shape[1]
                # out-of-range block ids: gathers clamp to a lane the zero
                # slot mask hides, scatters drop — a true no-op
                args.append(jnp.full((bucket, TB), paged.max_blocks, jnp.int32))
            state = refill_program(bucket)(*args)
        _warmed["done"] = True
        return state

    def _bucket_pad(input_ids: Any, prompt_mask: Any, table_rows: Any):
        """The shared bucket+pad protocol behind the refill and chunk host
        wrappers: round ``r`` up to the next power-of-two bucket; padding
        rows carry pad tokens, all-zero masks, and ``max_blocks``-poisoned
        block tables (every commit drops). Returns
        ``(bucket, pad, input_ids, prompt_mask, table_rows)``."""
        import numpy as np

        input_ids = np.asarray(input_ids, np.int32)
        prompt_mask = np.asarray(prompt_mask, np.int32)
        if table_rows is not None:
            table_rows = np.asarray(table_rows, np.int32)
        r = input_ids.shape[0]
        bucket = 1
        while bucket < r:
            bucket *= 2
        bucket = min(bucket, max(B, 1))
        if bucket < r:  # r > B cannot happen (more rows than slots)
            raise ValueError(f"refilling {r} rows into {B} slots")
        pad = bucket - r
        if pad:
            input_ids = np.concatenate(
                [input_ids, np.full((pad, P), config.pad_token_id, np.int32)]
            )
            prompt_mask = np.concatenate(
                [prompt_mask, np.zeros((pad, P), np.int32)]
            )
            if table_rows is not None:
                table_rows = np.concatenate(
                    [
                        table_rows,
                        np.full(
                            (pad, table_rows.shape[1]), paged.max_blocks,
                            np.int32,
                        ),
                    ]
                )
        return bucket, pad, input_ids, prompt_mask, table_rows

    def refill_rows(
        params: Any,
        state: SlotState,
        input_ids: Any,  # [r, P] host or device rows, r <= B
        prompt_mask: Any,
        slot_idx: Any,  # [r] distinct target slots
        new_keys: Any,
        table_rows: Any = None,  # [r, TB] block-table rows (paged only)
        hit: int = 0,  # committed leading cache columns (block-aligned)
    ) -> SlotState:
        """Host wrapper: round ``r`` up to the next power-of-two bucket
        (padding rows carry ``slot_idx = B`` and scatter-drop), so at most
        ``log2(B)+1`` refill programs ever compile per hit length while the
        prefill cost stays within 2× of the rows actually refilled."""
        import numpy as np

        slot_idx = np.asarray(slot_idx, np.int32)
        new_keys = np.asarray(new_keys)
        bucket, pad, input_ids, prompt_mask, table_rows = _bucket_pad(
            input_ids, prompt_mask, table_rows if paged is not None else None
        )
        if pad:
            slot_idx = np.concatenate([slot_idx, np.full((pad,), B, np.int32)])
            new_keys = np.concatenate(
                [new_keys, np.zeros((pad, 2), new_keys.dtype)]
            )
        args = [
            params, state, jnp.asarray(input_ids), jnp.asarray(prompt_mask),
            jnp.asarray(slot_idx), jnp.asarray(new_keys),
        ]
        if paged is not None:
            args.append(jnp.asarray(table_rows))
        return refill_program(bucket, hit)(*args)

    def decode_segment(params: Any, state: SlotState):
        """Up to ``segment_len`` decode steps over live slots; early exit
        when every slot is done. Returns ``(state, live_steps, steps_run)``
        — the utilization numerators/denominators for
        ``throughput/slot_utilization`` / ``rollout/padded_decode_frac``.

        Paged backend, ``decode_kernel: xla`` (the reference): gather the
        pool into the dense view once per segment, run the UNCHANGED dense
        loop on it, scatter each row's live writes (columns
        ``P + step_before .. P + step_after − 1``) back into its table's
        blocks. The loop body literally is the dense body over
        bit-identical values, so paged decode inherits the dense backend's
        bit-parity with plain ``generate``; the view is a per-program
        temporary.

        Paged backend, ``decode_kernel: pallas``: no view, no scatter —
        each step's forward reads K/V through the block table in place and
        commits its one column per live row through the table
        (``ops/paged_attention.py`` via ``models/transformer.py``), with
        fused top-k/top-p/temperature sampling. Frozen rows' table rows
        are poisoned out of range per step, so their dead writes drop —
        exactly the columns ``scatter_steps`` would not have committed.
        Bit-identical to the gather path (tests/test_paged_attention.py,
        tests/test_engine.py)."""
        if G:
            if decode_kernel == "pallas":
                return _spec_decode_segment_paged_kernel(params, state)
            return _spec_decode_segment(params, state)
        if paged is not None and decode_kernel == "pallas":
            return _decode_segment_paged_kernel(params, state)
        if paged is not None:
            paged_cache = state.cache
            view = gather_view(paged_cache.pool, paged_cache.block_table, S)
            step_before = state.step
            st, live_steps, steps = _decode_segment_dense(
                params, state._replace(cache=view)
            )
            pool = scatter_steps(
                paged_cache.pool,
                paged_cache.block_table,
                st.cache,
                P + step_before,
                st.step - step_before,
                segment_len,
            )
            return (
                st._replace(cache=PagedKV(pool, paged_cache.block_table)),
                live_steps,
                steps,
            )
        return _decode_segment_dense(params, state)

    def _spec_decode_segment(params: Any, state: SpecState):
        """Up to ``segment_len`` speculative ROUNDS over live slots — the
        round body is :func:`trlx_tpu.ops.speculative.spec_round_step`,
        shared verbatim with the solo sampler, so per-slot bit-parity is
        structural. One segment = one compiled program: fixed trip count
        (early exit when all slots finish), per-row live masks absorb the
        variable advancement — a row commits between 1 and ``G + 1``
        tokens per live round, bounded by ``segment_len · (G + 1)`` per
        segment — so the bucket never recompiles.

        Paged plumbing mirrors the plain xla segment: ONE pool gather into
        solo's dense ``[B, S]`` view on entry (each round's draft
        proposals, the single width-``G + 1`` target verify forward, and
        acceptance run on the view), ONE ``scatter_steps`` commit on exit.
        The committed span per row is ``[P + step_in − 1, P + step_out]``:
        the re-feed column ``c − 1`` is re-committed (its pool value was
        the residual-sampled token's never-forwarded placeholder — solo's
        dense cache holds the same re-feed result), accepted/bonus columns
        carry the verify's K/V, and REJECTED probe columns simply fall
        outside ``counts`` — ``scatter_steps`` poisons their lanes
        out-of-range exactly like frozen rows', so they drop instead of
        dirtying pool blocks another row may later receive."""
        t_params, d_params = params
        table = state.cache.block_table
        entry_live = ~state.done
        step_in = state.step
        view = gather_view(state.cache.pool, table, S)
        carry = {
            "rng": state.rng,
            "n_out": state.step,
            "done": state.done,
            "t_last": state.t_last,
            "t_cache": view,
            "d_cache": state.d_cache,
            "tokens": state.tokens,
            "logprobs": state.logprobs,
            "values": state.values,
            "mask": state.mask,
            "rounds": state.rounds,
            "accepted": state.accepted,
            "live_rounds": state.live_rounds,
            "committed": state.committed,
        }

        def body(c):
            cr, k = c
            return (
                spec_round_step(
                    cr,
                    prompt_mask=state.prompt_mask,
                    target_apply=apply_fn,
                    target_params=t_params,
                    draft_apply=draft_apply,
                    draft_params=d_params,
                    config=config,
                    G=G,
                    transition_mask=transition_mask,
                    adjust_logits=adjust_logits,
                ),
                k + 1,
            )

        def cond(c):
            cr, k = c
            return (k < segment_len) & ~jnp.all(cr["done"])

        final, _ = jax.lax.while_loop(
            cond, body, (carry, jnp.asarray(0, jnp.int32))
        )
        pool = scatter_steps(
            state.cache.pool,
            table,
            final["t_cache"],
            P + step_in - 1,
            jnp.where(entry_live, final["n_out"] - step_in + 1, 0),
            segment_len * (G + 1) + 1,
        )
        new_state = SpecState(
            tokens=final["tokens"],
            logprobs=final["logprobs"],
            values=final["values"],
            mask=final["mask"],
            prompt_mask=state.prompt_mask,
            cache=PagedKV(pool, table),
            d_cache=final["d_cache"],
            t_last=final["t_last"],
            prompt_len=state.prompt_len,
            done=final["done"],
            step=final["n_out"],
            rng=final["rng"],
            rounds=final["rounds"],
            accepted=final["accepted"],
            live_rounds=final["live_rounds"],
            committed=final["committed"],
        )
        # same (state, live_steps, steps) contract as the plain segment,
        # in ROUND units (slot_utilization keeps its live/total meaning;
        # token-level throughput is the spec_* gauges' job)
        return (
            new_state,
            final["live_rounds"] - state.live_rounds,
            final["rounds"] - state.rounds,
        )

    def _spec_decode_segment_paged_kernel(params: Any, state: SpecState):
        """The in-place twin of ``_spec_decode_segment``: the round body is
        still :func:`trlx_tpu.ops.speculative.spec_round_step` — verbatim —
        but the target cache threaded through it is the block pool with a
        per-round done-poisoned table attached instead of a gathered dense
        view, so each round's width-``G + 1`` verify forward reads K/V via
        the multi-position verify kernel
        (``ops/paged_attention.py::paged_verify_attention``, per-row probe
        windows ``[c − 1, c + G)`` through ``models/transformer.py``'s
        vector-``cache_index`` paged branch) and commits those columns with
        drop-mode writes as it goes. No gather on entry, no
        ``scatter_steps`` on exit.

        Commit discipline vs the gather reference: the re-feed column
        ``c − 1`` is re-written with identical bits (same token, same
        position, same visible columns — the recompute the gather path's
        scatter also re-commits); accepted/bonus columns carry the verify's
        K/V; REJECTED probe columns are written in place where
        ``scatter_steps`` would have dropped them, but they sit strictly
        above every row's committed length, so slot-causal masking keeps
        them invisible to every later read — the same stale-value
        invariant recycled blocks already rely on. Rows that are done at a
        round's START get their table rows poisoned out of range (their
        blocks may already be recycled after harvest), exactly mirroring
        ``_decode_segment_paged_kernel``'s per-step freeze masking. The
        draft cache stays dense per slot — the draft never touches the
        pool."""
        t_params, d_params = params
        table = state.cache.block_table
        carry = {
            "rng": state.rng,
            "n_out": state.step,
            "done": state.done,
            "t_last": state.t_last,
            # the carry holds the BARE pool (stable pytree across rounds);
            # each round attaches a freshly poisoned table before the
            # shared round body and strips it after
            "t_cache": state.cache.pool,
            "d_cache": state.d_cache,
            "tokens": state.tokens,
            "logprobs": state.logprobs,
            "values": state.values,
            "mask": state.mask,
            "rounds": state.rounds,
            "accepted": state.accepted,
            "live_rounds": state.live_rounds,
            "committed": state.committed,
        }

        def body(c):
            cr, k = c
            eff_table = jnp.where(
                cr["done"][:, None], paged.max_blocks, table
            )
            cr = {
                **cr,
                "t_cache": attach_block_table(cr["t_cache"], eff_table),
            }
            cr = spec_round_step(
                cr,
                prompt_mask=state.prompt_mask,
                target_apply=apply_fn,
                target_params=t_params,
                draft_apply=draft_apply,
                draft_params=d_params,
                config=config,
                G=G,
                transition_mask=transition_mask,
                adjust_logits=adjust_logits,
            )
            cr = {**cr, "t_cache": detach_block_table(cr["t_cache"])}
            return cr, k + 1

        def cond(c):
            cr, k = c
            return (k < segment_len) & ~jnp.all(cr["done"])

        final, _ = jax.lax.while_loop(
            cond, body, (carry, jnp.asarray(0, jnp.int32))
        )
        new_state = SpecState(
            tokens=final["tokens"],
            logprobs=final["logprobs"],
            values=final["values"],
            mask=final["mask"],
            prompt_mask=state.prompt_mask,
            cache=PagedKV(final["t_cache"], table),
            d_cache=final["d_cache"],
            t_last=final["t_last"],
            prompt_len=state.prompt_len,
            done=final["done"],
            step=final["n_out"],
            rng=final["rng"],
            rounds=final["rounds"],
            accepted=final["accepted"],
            live_rounds=final["live_rounds"],
            committed=final["committed"],
        )
        return (
            new_state,
            final["live_rounds"] - state.live_rounds,
            final["rounds"] - state.rounds,
        )

    def _decode_segment_paged_kernel(params: Any, state: SlotState):
        """The in-place twin of ``_decode_segment_dense``: same sampling
        and bookkeeping ops on the same values, but the cache threaded
        through ``apply_fn`` is the block pool + (live-masked) table
        instead of a gathered dense view, and sampling runs the fused
        kernel. The per-row sample/bookkeeping stream is bit-identical by
        construction of the two kernels."""
        from trlx_tpu.ops.paged_attention import sample_token_fused

        table = state.cache.block_table

        def step_cache(st: SlotState, live: jax.Array):
            # freeze-mask the table EVERY step: a row that finished mid-
            # segment must stop committing K/V (its blocks may already be
            # recycled after harvest) — out-of-range ids drop all writes
            eff_table = jnp.where(live[:, None], table, paged.max_blocks)
            return attach_block_table(st.cache.pool, eff_table)

        def fold_cache(out_cache: Any) -> PagedKV:
            return PagedKV(detach_block_table(out_cache), table)

        return _segment_loop(
            params, state, step_cache, fold_cache, sample_token_fused
        )

    def _decode_segment_dense(params: Any, state: SlotState):
        return _segment_loop(
            params,
            state,
            lambda st, live: st.cache,
            lambda out_cache: out_cache,
            sample_token_from_logits,
        )

    def _segment_loop(params, state, step_cache, fold_cache, sample_fn):
        def sample_step(carry):
            st, live_steps, k = carry
            new_rng, sample_rng = split_row_keys(st.rng)
            next_token, logprob = sample_fn(
                st.logits, st.step_out, sample_rng, config, st.step, adjust_logits
            )
            live = ~st.done
            next_token = jnp.where(live, next_token, config.pad_token_id).astype(jnp.int32)
            tokens = _row_set(st.tokens, next_token, st.step, live)
            logprobs = _row_set(st.logprobs, jnp.where(live, logprob, 0.0), st.step, live)
            value = st.step_out.get("value", jnp.zeros((B,), jnp.float32))
            values = _row_set(st.values, jnp.where(live, value, 0.0), st.step, live)
            mask = _row_set(st.mask, live.astype(jnp.int32), st.step, live)

            done = st.done
            if config.eos_token_id is not None:
                done = done | (live & (next_token == config.eos_token_id))
            # a live row that just wrote its N-th column is finished even
            # without eos — plain generate's loop exits at step N; here the
            # row must freeze so the next (clamped) write can't clobber its
            # last column while it awaits harvest
            done = done | (live & (st.step + 1 >= N))

            slot = P + st.step  # [B] per-slot cache column
            slot_mask = _row_set(st.slot_mask, live.astype(jnp.int32), slot, live)

            out = apply_fn(
                params,
                next_token[:, None],
                attention_mask=slot_mask,
                positions=(st.prompt_len + st.step)[:, None],
                cache=step_cache(st, live),
                cache_index=slot,
            )
            step_out = {**last_step_info(out), "last_tokens": next_token}
            new_st = SlotState(
                tokens=tokens,
                logprobs=logprobs,
                values=values,
                mask=mask,
                slot_mask=slot_mask,
                # dense view: the forward wrote every row's k/v at its own
                # slot (done rows into dead masked columns — harmless);
                # in-place kernel: only live rows committed, through the
                # live-masked table
                cache=fold_cache(out["cache"]),
                logits=_row_where(live, out["logits"][:, -1, :], st.logits),
                step_out=_row_where(live, step_out, st.step_out),
                prompt_len=st.prompt_len,
                done=done,
                step=jnp.where(live, st.step + 1, st.step),
                rng=_row_where(live, new_rng, st.rng),
            )
            return new_st, live_steps + jnp.sum(live.astype(jnp.int32)), k + 1

        def cond(carry):
            st, _, k = carry
            return (k < segment_len) & ~jnp.all(st.done)

        st, live_steps, steps = jax.lax.while_loop(
            cond, sample_step, (state, jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
        )
        return st, live_steps, steps

    if jit:
        decode_segment = jax.jit(decode_segment)
    return SlotRefillFns(
        init_state=empty_spec_state if G else empty_state,
        refill_rows=refill_rows,
        refill_program=refill_program,
        prewarm=prewarm,
        decode_segment=decode_segment,
        batch_size=B,
        prompt_len=P,
        max_new_tokens=N,
        segment_len=segment_len,
        paged=paged,
        decode_kernel=decode_kernel,
        prefill_kernel=prefill_kernel,
        prefill_chunk_rows=prefill_chunk_rows if paged is not None else None,
        prefill_chunk_program=(
            prefill_chunk_program if paged is not None else None
        ),
        speculative=G,
    )
