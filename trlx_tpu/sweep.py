"""HPO sweep runner: dot-path hyperparameter spaces over a user script.

Capability parity with ``trlx/sweep.py:17-267`` (Ray Tune), rebuilt without a
Ray dependency: trials are subprocesses of the user script (same isolation
property Ray gave the reference — a fresh JAX runtime per trial, no compiled
-program or global-mesh leakage), the search space grammar is identical
(``strategy`` + ``values`` per dot-path key, ``tune_config`` block), and
results aggregate into a JSONL table + ranked report instead of a W&B
report (``trlx/sweep.py:177-264``).

Usage (same CLI shape as the reference)::

    python -m trlx_tpu.sweep --config examples/sweeps/ppo_sweep.yml \
        examples/randomwalks/ppo_randomwalks.py

The user script must expose ``main(hparams: dict)`` (every example does);
each trial invokes ``script.py '<json hparams>'`` with
``TRLX_TPU_SWEEP_RESULT`` pointing at the trial's result file, which the
trainer's learn loop writes at every evaluation (so early-stopped or crashed
trials still report their last metric).

Search algorithms: ``random`` (reference default), ``grid`` (via
``grid`` strategies), ``quasirandom`` (Halton — lower discrepancy coverage
than random at small trial counts; beyond the reference), and ``bayesopt``
(alias ``tpe``): an in-repo Tree-structured Parzen Estimator — the
reference's adaptive-search capability (``trlx/sweep.py:103-133``, Ray's
``BayesOptSearch``/``TuneBOHB``) without the external dependency. Every
strategy is a deterministic map from a unit coordinate ``u`` ∈ [0,1), so
all three samplers share one space: random draws u uniformly, quasirandom
from a Halton sequence, and TPE models completed trials' u-vectors with
good/bad Parzen mixtures and proposes the candidate maximizing their
density ratio. Schedulers: ``fifo`` (every trial runs its full budget) or
``asha``/``hyperband`` — successive halving over a budget dot-path (the
reference's Ray HyperBandScheduler capability, adapted to sequential
subprocess trials: promotions rerun at the larger budget).

Cluster dispatch (the reference's Ray trial placement,
``trlx/sweep.py:267-348``), all via ``tune_config``:

- ``launcher``: shell-line template used to start each trial process,
  e.g. ``"ssh -tt {host} env {env_remote} {python} {script}
  {hparams_remote}"`` — ``{env}``/``{env_remote}`` expand to the trial's
  ``TRLX_TPU_*`` contract (+ ``PYTHONPATH``) as ``k=v`` assignments (remote
  shells don't inherit the sweep's environment); the ``_remote`` variants
  carry an extra quoting layer that survives the remote shell's re-split,
  and ``-tt`` makes a terminated ssh client hang up the remote trial;
- ``hosts``: a free-slot pool — each trial borrows an entry for its
  whole run, so two in-flight trials never share one. Entries are a host
  or a comma-separated group (one process per pod host, coordinator on
  the first). Accelerator trials parallelize across hosts up to one
  in-flight trial per host (clamped);
- ``procs_per_trial``: spawn N coordinated processes per trial over the
  ``TRLX_TPU_COORDINATOR``/``NUM_PROCESSES``/``PROCESS_ID`` multi-host
  contract (one trial = one jax.distributed cluster; rank 0 writes the
  result file).

Reporting: trials stream a per-trial JSONL tracker under the sweep dir
(``tune_config.trial_curves: false`` keeps the script's own tracker), and
``report.md`` renders the ranked table plus each trial's metric curve
(sparklines; raw series in ``curves.json``) — the reference's W&B-report
capability offline. ``tune_config.wandb_report: true`` additionally
publishes the curves to a W&B run (opt-in: an unauthenticated wandb.init
blocks on a login prompt).

Results flow through ``TRLX_TPU_SWEEP_RESULT`` paths under the sweep's
output dir, so remote hosts must share that filesystem (NFS/GCS-fuse — the
standard pod setup; Ray ships results through its object store instead).
"""

import argparse
import functools
import importlib.util
import itertools
import json
import os
import re
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np
import yaml

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

_PRIMES = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53)


def _norm_inv_cdf(u: float) -> float:
    """Standard-normal inverse CDF (stdlib; keeps randn strategies u-driven)."""
    from statistics import NormalDist

    return NormalDist().inv_cdf(min(max(u, 1e-9), 1 - 1e-9))


def _halton(index: int, base: int) -> float:
    """Van der Corput radical inverse of ``index`` in ``base`` ∈ (0, 1)."""
    result, f = 0.0, 1.0
    i = index
    while i > 0:
        f /= base
        result += f * (i % base)
        i //= base
    return result


@dataclass
class ParamDef:
    """One swept hyperparameter: a dot-path key + sampling strategy."""

    key: str
    strategy: str
    values: List[Any]

    def sample(self, u: float, rng: Optional[np.random.RandomState] = None) -> Any:
        """Map a unit coordinate ``u`` ∈ [0,1) to a value. Every strategy is
        a deterministic function of ``u`` so random, quasirandom, and TPE
        sampling all operate in one shared unit cube (``rng`` is accepted
        for backward compatibility and unused)."""
        del rng
        s, v = self.strategy, self.values
        if s == "uniform":
            return float(v[0] + u * (v[1] - v[0]))
        if s == "quniform":
            q = v[2]
            return float(np.round((v[0] + u * (v[1] - v[0])) / q) * q)
        if s == "loguniform":
            lo, hi = np.log(v[0]), np.log(v[1])
            return float(np.exp(lo + u * (hi - lo)))
        if s == "qloguniform":
            lo, hi, q = np.log(v[0]), np.log(v[1]), v[3]
            return float(np.round(np.exp(lo + u * (hi - lo)) / q) * q)
        if s == "randn":
            mean, sd = v
            return float(mean + sd * _norm_inv_cdf(u))
        if s == "qrandn":
            mean, sd, q = v
            return float(np.round((mean + sd * _norm_inv_cdf(u)) / q) * q)
        if s == "randint":
            return int(v[0] + int(u * (v[1] - v[0])))
        if s == "qrandint":
            q = v[2]
            return int(np.round((v[0] + u * (v[1] - v[0])) / q) * q)
        if s == "lograndint":
            lo, hi = np.log(v[0]), np.log(v[1])
            return int(np.exp(lo + u * (hi - lo)))
        if s == "qlograndint":
            lo, hi, q = np.log(v[0]), np.log(v[1]), v[3]
            return int(np.round(np.exp(lo + u * (hi - lo)) / q) * q)
        if s == "choice":
            return v[min(int(u * len(v)), len(v) - 1)]
        raise ValueError(f"Unknown strategy '{s}' for {self.key}")


@dataclass
class SweepSpace:
    """Parsed sweep config: sampled params + grid params + tune settings."""

    sampled: List[ParamDef] = field(default_factory=list)
    grid: List[ParamDef] = field(default_factory=list)
    tune: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "SweepSpace":
        space = cls()
        for key, value in config.items():
            if key in ("tune_config", "tune"):
                space.tune = dict(value)
                continue
            if not isinstance(value, dict) or "strategy" not in value:
                raise ValueError(
                    f"Sweep entry '{key}' must be a dict with 'strategy' and 'values'"
                )
            pd = ParamDef(key, value["strategy"], value.get("values", []))
            (space.grid if pd.strategy == "grid" else space.sampled).append(pd)
        return space

    def grid_points(self) -> List[Dict[str, Any]]:
        """Cartesian product of the grid-strategy params (``[{}]`` if none)."""
        if not self.grid:
            return [{}]
        grid_axes = [[(p.key, v) for v in p.values] for p in self.grid]
        return [dict(combo) for combo in itertools.product(*grid_axes)]

    def realize(self, point: Dict[str, Any], us: np.ndarray) -> Dict[str, Any]:
        """One grid point + a unit-cube coordinate vector → hparam dict."""
        hp = dict(point)
        for j, p in enumerate(self.sampled):
            hp[p.key] = p.sample(float(us[j]))
        return hp

    def trials(self, num_samples: int, seed: int = 0, search_alg: str = "random") -> Iterator[Dict[str, Any]]:
        """Yield hparam dicts: the cartesian grid × ``num_samples`` draws of
        the sampled params (non-adaptive algorithms only — ``bayesopt``
        needs trial feedback and runs through :func:`run_sweep`)."""
        searcher = Searcher(len(self.sampled), search_alg, seed)
        if searcher.adaptive:
            raise ValueError(
                f"search_alg '{search_alg}' is adaptive — it proposes trials "
                "from completed results and only runs through run_sweep()"
            )
        for _ in range(max(1, num_samples)):
            us = searcher.propose([])
            for point in self.grid_points():
                yield self.realize(point, us)
                if searcher.alg == "random":
                    # fresh coordinates per grid point: random explores
                    # |grid| x num_samples distinct sampled configs
                    # (quasirandom keeps one Halton row per draw)
                    us = searcher.propose([])


class Searcher:
    """Sequential trial proposer over the unit cube shared by every
    :class:`ParamDef` strategy.

    - ``random``: i.i.d. uniform (the reference's Ray Tune default).
    - ``quasirandom``: Halton sequence — stratified coverage at small trial
      counts (beyond the reference).
    - ``bayesopt`` / ``tpe``: Tree-structured Parzen Estimator, the adaptive
      capability the reference delegates to Ray's BayesOptSearch/TuneBOHB
      (``trlx/sweep.py:103-133``). After a quasirandom warmup, completed
      trials are split into good/bad by metric quantile (γ = 0.25); per
      dimension a Parzen mixture (Gaussians at observed coordinates + a
      uniform prior component) models each set, candidates are drawn from
      the good mixture, and the one maximizing ``log l(u|good) −
      log l(u|bad)`` is proposed — expected-improvement-proportional
      acquisition, per Bergstra et al. 2011.
    """

    def __init__(
        self,
        ndims: int,
        alg: str = "random",
        seed: int = 0,
        gamma: float = 0.25,
        n_candidates: int = 24,
        n_startup: Optional[int] = None,
    ):
        if alg not in ("random", "quasirandom", "bayesopt", "tpe"):
            raise ValueError(
                f"search_alg '{alg}' not supported "
                "(random, quasirandom, bayesopt/tpe)"
            )
        self.ndims = ndims
        self.alg = alg
        self.rng = np.random.RandomState(seed)
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.n_startup = n_startup or max(4, 2 * ndims)
        self._draw = 0

    @property
    def adaptive(self) -> bool:
        return self.alg in ("bayesopt", "tpe")

    def propose(self, history: List[Tuple[List[float], float]]) -> np.ndarray:
        """Next unit-cube point. ``history`` holds completed trials as
        ``(u_vector, metric)`` with larger metric = better (callers negate
        for minimization); non-adaptive algorithms ignore it."""
        self._draw += 1
        halton_row = np.array(
            [_halton(self._draw, _PRIMES[j % len(_PRIMES)]) for j in range(self.ndims)]
        )
        if self.alg == "random":
            return self.rng.rand(self.ndims)
        if self.alg == "quasirandom" or len(history) < self.n_startup:
            return halton_row
        ordered = sorted(history, key=lambda t: -t[1])
        n_good = max(2, int(np.ceil(self.gamma * len(ordered))))
        good = np.asarray([u for u, _ in ordered[:n_good]], float)
        bad = np.asarray([u for u, _ in ordered[n_good:]], float)
        us = np.empty(self.ndims)
        for j in range(self.ndims):
            cands = self._parzen_draw(good[:, j])
            score = self._parzen_logpdf(cands, good[:, j]) - self._parzen_logpdf(
                cands, bad[:, j] if bad.size else np.empty(0)
            )
            us[j] = cands[int(np.argmax(score))]
        return us

    @staticmethod
    def _bandwidth(n: int) -> float:
        return float(np.clip(1.06 * 0.3 / max(n, 1) ** 0.2, 0.06, 0.5))

    def _parzen_draw(self, centers: np.ndarray) -> np.ndarray:
        """Candidates from the good mixture (uniform component included)."""
        bw = self._bandwidth(len(centers))
        picks = self.rng.randint(-1, len(centers), size=self.n_candidates)
        cands = np.where(
            picks < 0,
            self.rng.rand(self.n_candidates),
            centers[np.clip(picks, 0, None)] + bw * self.rng.randn(self.n_candidates),
        )
        return np.clip(cands, 0.0, 1.0 - 1e-9)

    def _parzen_logpdf(self, x: np.ndarray, centers: np.ndarray) -> np.ndarray:
        """log density of the Parzen mixture: Gaussians at ``centers`` plus
        one uniform prior component (keeps the ratio bounded off-support)."""
        if centers.size == 0:
            return np.zeros_like(x)
        bw = self._bandwidth(len(centers))
        z = (x[:, None] - centers[None, :]) / bw
        comps = np.exp(-0.5 * z**2) / (bw * np.sqrt(2 * np.pi))
        dens = (comps.sum(axis=1) + 1.0) / (len(centers) + 1)
        return np.log(dens + 1e-12)


_PORT_LOCK = threading.Lock()
_PORT_COUNTER = itertools.count(29500 + (os.getpid() % 997))


def _next_coordinator_port() -> int:
    """Sweep-unique coordinator port. A bind-then-release probe would race
    under concurrent trials (two trials drawing the same ephemeral port and
    cross-joining into one jax.distributed cluster) and proves nothing for a
    remote host anyway; a monotonic counter from a pid-offset base keeps
    every trial in this sweep on its own port. Collisions with unrelated
    services surface as an init failure of that one trial."""
    with _PORT_LOCK:
        return next(_PORT_COUNTER)


# the launcher template's placeholder names — substituted by literal token
# match (NOT str.format, whose index/attr/format-spec parsing corrupts shell
# constructs like ${arr[0]}, ${VAR:-default} or awk {print})
_PLACEHOLDERS = (
    "python", "script", "hparams", "hparams_remote", "host", "env", "env_remote"
)
_LAUNCHER_TOKENS = re.compile(r"\{(%s)\}" % "|".join(_PLACEHOLDERS))

# {token}-shaped survivors of substitution, for the typo check below; `$`
# lookbehind keeps shell ${VAR} expansions out, and the bare-word shape keeps
# awk '{print $1}' and friends out
_BRACE_TOKEN = re.compile(r"(?<!\$)\{([A-Za-z_][A-Za-z0-9_]*)\}")


@functools.lru_cache(maxsize=None)
def _warn_placeholder_near_misses(launcher: str) -> None:
    """A typo'd placeholder is not an error to the template engine — only the
    exact tokens substitute, so ``{pyhton}``, ``{hparam}``, or ``{HOST}``
    ride into the shell verbatim and the trial fails (or silently misruns)
    far from the typo. Scans the *template with the known tokens stripped*
    (never the substituted values — an hparam whose text contains
    ``{host}`` is the user's business) and warns for any surviving
    ``{token}`` that is case-insensitively equal or close (difflib ≥ 0.8) to
    a known placeholder; genuine shell/awk braces don't resemble one and
    stay silent. ``lru_cache``: the template is fixed for a sweep's
    lifetime, so the diagnosis prints once, not once per trial."""
    import difflib

    known = sorted(_PLACEHOLDERS)
    for token in _BRACE_TOKEN.findall(_LAUNCHER_TOKENS.sub("", launcher)):
        lowered = token.lower()
        if lowered in known:
            hint = lowered  # wrong case — {PYTHON} is not {python}
        else:
            close = difflib.get_close_matches(lowered, known, n=1, cutoff=0.8)
            if not close:
                continue
            hint = close[0]
        logger.warning(
            "launcher template: '{%s}' survived substitution but looks like "
            "the placeholder '{%s}' — it will reach the shell verbatim; "
            "known placeholders: %s",
            token, hint, ", ".join("{%s}" % k for k in known),
        )


def _trial_command(
    launcher: Optional[str],
    script: str,
    hparams: Dict[str, Any],
    host: Optional[str],
    env: Dict[str, str],
    extra_keys: Tuple[str, ...] = (),
):
    """Build one trial process's command: an argv list (no launcher) or a
    shell line (launcher template — run with ``shell=True`` so it behaves
    like the line the user wrote).

    Template placeholders: ``{python}``, ``{script}``, ``{host}``,
    ``{hparams}`` / ``{env}`` (shell-quoted once — for commands executed
    locally), and ``{hparams_remote}`` / ``{env_remote}`` (quoted twice —
    one layer is consumed by the local shell, the surviving layer protects
    the value when a remote shell re-splits the line, as ssh does). ``{env}``
    carries the trial's ``TRLX_TPU_*`` contract plus ``PYTHONPATH`` and
    ``JAX_PLATFORMS`` as ``k=v`` assignments: remote shells don't inherit
    the sweep's environment. Example::

        launcher: "ssh -tt {host} env {env_remote} {python} {script} {hparams_remote}"

    (``-tt`` so terminating the local ssh client also hangs up the remote
    trial — plain ssh would leave it running, holding the host's chip.)

    ONLY the exact tokens above are substituted (literal regex match, not
    ``str.format``); everything else — shell ``${HOME}``, ``${arr[0]}``,
    ``${VAR:-default}``, awk ``{print}``, lone braces — passes through
    verbatim with no escaping needed. ``{env}`` also carries every key the
    caller passed via ``extra_env`` (``extra_keys``) — a user-supplied
    ``WANDB_API_KEY`` or ``XLA_FLAGS`` must reach remote trials exactly
    like local no-launcher ones.

    Pass-through is also where typos hide: a ``{token}`` that *almost* names
    a placeholder (``{pyhton}``, ``{hparam}``, ``{HOST}``) survives
    substitution and reaches the shell verbatim, so the template is scanned
    and near-misses are warned about (genuine shell/awk braces and brace
    text inside substituted *values* stay silent — see
    :func:`_warn_placeholder_near_misses`).
    """
    if launcher is None:
        return [sys.executable, os.path.abspath(script), json.dumps(hparams)]
    import shlex

    def env_pairs(quote):
        return " ".join(
            f"{k}={quote(v)}"
            for k, v in sorted(env.items())
            if k.startswith("TRLX_TPU_")
            or k in ("JAX_PLATFORMS", "PYTHONPATH")
            or k in extra_keys
        )

    payload = json.dumps(hparams)
    values = {
        "python": shlex.quote(sys.executable),
        "script": shlex.quote(os.path.abspath(script)),
        "hparams": shlex.quote(payload),
        "hparams_remote": shlex.quote(shlex.quote(payload)),
        "host": host or "localhost",
        "env": env_pairs(shlex.quote),
        "env_remote": env_pairs(lambda v: shlex.quote(shlex.quote(v))),
    }
    _warn_placeholder_near_misses(launcher)
    return _LAUNCHER_TOKENS.sub(lambda m: values[m.group(1)], launcher)


def _wait_sigterm_only(procs: List[subprocess.Popen], timeout: Optional[float], log) -> int:
    """Wait on every trial process; on timeout SIGTERM (twice) then ORPHAN —
    never SIGKILL: a process hung on the accelerator claim that is SIGKILLed
    wedges the chip for every subsequent trial. Returns max rc (-1 on
    timeout/orphan)."""
    deadline = None if timeout is None else time.time() + timeout
    rc = 0
    timed_out = False
    for proc in procs:
        left = None if deadline is None else max(0.1, deadline - time.time())
        try:
            rc = max(rc, abs(proc.wait(timeout=left)))
            continue
        except subprocess.TimeoutExpired:
            pass
        timed_out = True
        terminated = False

        def _sigterm(p=proc):
            # shell-launched trials run in their own session: signal that
            # whole group so the SIGTERM reaches the trial, not just /bin/sh.
            # ONLY when the child leads its own group — killpg on a child in
            # the sweep's group would SIGTERM the sweep itself.
            import signal

            try:
                pgid = os.getpgid(p.pid)
                if pgid == p.pid:
                    os.killpg(pgid, signal.SIGTERM)
                else:
                    p.terminate()
            except (ProcessLookupError, PermissionError, OSError):
                p.terminate()

        for _ in range(2):
            _sigterm()
            try:
                proc.wait(timeout=30)
                log.write(f"\nsweep: trial terminated after {timeout}s timeout\n")
                terminated = True
                break
            except subprocess.TimeoutExpired:
                continue
        if not terminated:
            log.write(
                f"\nsweep: trial pid {proc.pid} ignored SIGTERM after "
                f"{timeout}s timeout; orphaned (never SIGKILL — chip wedge)\n"
            )
    # a real failure code from any process outranks the generic timeout mark
    return rc if rc > 0 else (-1 if timed_out else rc)


def run_trial(
    script: str,
    hparams: Dict[str, Any],
    result_path: str,
    log_path: str,
    timeout: Optional[float] = None,
    extra_env: Optional[Dict[str, str]] = None,
    launcher: Optional[str] = None,
    host: Optional[str] = None,
    procs_per_trial: int = 1,
) -> int:
    """One trial: ``python script.py '<json>'`` with the result file
    advertised via ``TRLX_TPU_SWEEP_RESULT``.

    Multi-host dispatch (the reference's Ray-cluster trial placement,
    ``trlx/sweep.py:267-348``): ``launcher`` is a command template (see
    :func:`_trial_command`) used to place the processes — e.g. over ssh —
    and ``procs_per_trial > 1`` spawns that many coordinated processes per
    trial over the ``TRLX_TPU_COORDINATOR``/``NUM_PROCESSES``/``PROCESS_ID``
    contract (``trlx_tpu.trlx.initialize_runtime``). ``host`` may be a
    comma-separated group (``"hostA,hostB"``): process ``i`` lands on
    ``group[i % len(group)]`` — one process per pod host — and the
    coordinator is process 0's host. The trainer reports sweep results from
    rank 0 only, so the one ``result_path`` stays single-writer."""
    env = dict(os.environ)
    # trials run with cwd at the script; any relative path we hand them
    # would resolve against that cwd, not the sweep's
    env["TRLX_TPU_SWEEP_RESULT"] = os.path.abspath(result_path)
    # trials run with cwd at the script (for its local imports); make this
    # trlx_tpu installation importable there too
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH")) if p
    )
    if extra_env:
        env.update(extra_env)
    group = (host or "localhost").split(",")
    coordinator = None
    if procs_per_trial > 1:
        coordinator = f"{group[0]}:{_next_coordinator_port()}"
    with open(log_path, "a") as log:
        procs = []
        for pid_i in range(max(1, procs_per_trial)):
            penv = dict(env)
            if coordinator is not None:
                penv.update(
                    TRLX_TPU_COORDINATOR=coordinator,
                    TRLX_TPU_NUM_PROCESSES=str(procs_per_trial),
                    TRLX_TPU_PROCESS_ID=str(pid_i),
                )
            cmd = _trial_command(
                launcher, script, hparams, group[pid_i % len(group)], penv,
                extra_keys=tuple(extra_env or ()),
            )
            procs.append(
                subprocess.Popen(
                    cmd,
                    shell=isinstance(cmd, str),
                    # own session, so timeout SIGTERMs reach the whole
                    # launcher process group (shell + ssh client)
                    start_new_session=isinstance(cmd, str),
                    cwd=os.path.dirname(os.path.abspath(script)) or None,
                    env=penv,
                    stdout=log,
                    stderr=subprocess.STDOUT,
                )
            )
        return _wait_sigterm_only(procs, timeout, log)


def run_sweep(
    script: str,
    config: Dict[str, Any],
    output_dir: str,
    num_samples: Optional[int] = None,
    seed: int = 0,
    trial_timeout: Optional[float] = None,
    extra_env: Optional[Dict[str, str]] = None,
    max_concurrent: int = 1,
) -> List[Dict[str, Any]]:
    """Run the sweep's trials (subprocesses of the user script), logging a
    JSONL results table, and return the records ranked best-first.

    Concurrency (``max_concurrent`` / ``tune_config.max_concurrent``): up to
    N trials run at once in a subprocess pool, the reference's Ray Tune
    parallel-trials capability (``trlx/sweep.py:267-347``, per-trial
    resources).  Parallel trials only make sense on a CPU mesh (one process
    per trial); when the trials would target a single accelerator the sweep
    serializes automatically with a warning — pass
    ``extra_env={"JAX_PLATFORMS": "cpu"}`` (CLI ``--cpu-trials``) to opt
    into parallel CPU trials.  Adaptive search (TPE) under concurrency
    proposes in chunks of ``max_concurrent`` from the history completed so
    far — the same stale-history compromise Ray makes.

    Schedulers (``tune_config.scheduler``): ``fifo`` (default — every trial
    runs its full budget, the reference's default) or ``asha``/``hyperband``
    — synchronous successive halving, the reference's Ray
    ``HyperBandScheduler`` capability (``trlx/sweep.py:136-174``): the
    initial population runs at a small budget (``grace_period`` steps of the
    ``budget_key`` dot-path, default ``train.total_steps``), the top
    ``1/reduction_factor`` fraction is promoted to an ``eta``-times larger
    budget, repeating until ``max_t``.  By default promoted trials RESUME
    from the rung's final interval checkpoint (each config gets a private
    ``train.checkpoint_dir`` under the sweep dir and promotions set
    ``train.resume_from_checkpoint``); set ``tune_config.asha_resume: false``
    to rerun promotions from scratch instead (e.g. when the user script
    overrides checkpointing itself).
    """
    space = SweepSpace.from_config(config)
    tune = space.tune
    metric = tune.get("metric", "reward/mean")
    mode = tune.get("mode", "max")
    n = num_samples or int(tune.get("num_samples", 4))
    search_alg = tune.get("search_alg", "random")
    scheduler = tune.get("scheduler", "fifo")
    if scheduler not in ("fifo", "asha", "hyperband"):
        raise ValueError(
            f"scheduler '{scheduler}' not supported (fifo, asha/hyperband)"
        )
    max_concurrent = max(1, int(tune.get("max_concurrent", max_concurrent)))
    # cluster dispatch (reference: Ray trial placement, trlx/sweep.py:267-348)
    launcher = tune.get("launcher")
    hosts: List[str] = list(tune.get("hosts") or [])
    procs_per_trial = max(1, int(tune.get("procs_per_trial", 1)))
    trial_curves = bool(tune.get("trial_curves", True))
    wandb_report = bool(tune.get("wandb_report", False))
    if hosts and launcher is None:
        raise ValueError(
            "tune_config.hosts needs tune_config.launcher (a command template "
            "like \"ssh -tt {host} env {env_remote} {python} {script} "
            "{hparams_remote}\") to place trials on those hosts"
        )
    # TRLX_TPU_PLATFORM is the authoritative CPU-forcing contract
    # (initialize_runtime overrides boot shims that ignore JAX_PLATFORMS);
    # fall back to JAX_PLATFORMS for scripts that don't call it
    merged_env = dict(os.environ)
    merged_env.update(extra_env or {})
    trial_platform = merged_env.get(
        "TRLX_TPU_PLATFORM", merged_env.get("JAX_PLATFORMS", "")
    )
    if hosts and max_concurrent > len(hosts) and trial_platform.lower() != "cpu":
        # accelerator trials take a host-pool slot for their whole run, so
        # excess in-flight trials would just block on the pool; clamp loudly
        # instead of silently queueing (CPU trials are exempt below: host
        # sharing is safe there, so they skip the pool entirely)
        logger.warning(
            f"max_concurrent={max_concurrent} > {len(hosts)} hosts with "
            "accelerator trials; clamping to one in-flight trial per host"
        )
        max_concurrent = len(hosts)
    if max_concurrent > 1 and trial_platform.lower() != "cpu" and not hosts:
        logger.warning(
            f"max_concurrent={max_concurrent} but trials target the "
            "accelerator (JAX_PLATFORMS is not 'cpu'); a single chip cannot "
            "host concurrent trials — serializing. Pass --cpu-trials (or "
            "extra_env JAX_PLATFORMS=cpu) for parallel CPU-mesh trials."
        )
        max_concurrent = 1

    # trials run with their cwd at the user script — every path that crosses
    # the subprocess boundary (result files, per-trial logging dirs) must be
    # absolute or it lands next to the script instead of the sweep output
    output_dir = os.path.abspath(output_dir)
    os.makedirs(output_dir, exist_ok=True)
    results_path = os.path.join(output_dir, "results.jsonl")
    records: List[Dict[str, Any]] = []
    # Host assignment. Accelerator trials: a free-slot pool — a trial
    # borrows a host for its whole run, so two in-flight trials can never
    # share one chip (index-based cycling breaks the moment pool workers
    # finish out of order, e.g. big ASHA batches). CPU trials: host sharing
    # is safe, so skip the pool — a blocking pool would silently serialize
    # the supported oversubscribed-CPU sweep — and cycle hosts non-blocking.
    host_pool: Optional[Any] = None
    host_cycle: Optional[Any] = None
    if hosts:
        if trial_platform.lower() != "cpu":
            import queue

            host_pool = queue.Queue()
            for h in hosts:
                host_pool.put(h)
        else:
            host_cycle = iter(itertools.cycle(hosts))
    searcher = Searcher(len(space.sampled), search_alg, seed=seed)
    grid_points = space.grid_points()
    draws = max(1, n)
    sign = 1.0 if mode == "max" else -1.0
    lock = threading.Lock()
    logger.info(
        f"Sweep[{search_alg}/{scheduler}"
        + (f"/x{max_concurrent}" if max_concurrent > 1 else "")
        + f"]: {draws * len(grid_points)} base trials "
        f"of {os.path.basename(script)} → {output_dir}"
    )

    with open(results_path, "w") as results_f:

        def launch(hparams: Dict[str, Any], us: np.ndarray, rung: Optional[int] = None) -> Dict[str, Any]:
            with lock:  # reserve a trial index
                i = len(records)
                record: Dict[str, Any] = {"trial": i, "metric": None}
                records.append(record)
            t0 = time.time()
            result_path = os.path.join(output_dir, f"trial_{i:03d}.json")
            log_path = os.path.join(output_dir, f"trial_{i:03d}.log")
            # per-trial metric curves (the reference streams every trial to
            # W&B and renders a report of the curves, trlx/sweep.py:177-264;
            # here each trial gets a JSONL tracker under the sweep dir and
            # report() renders the curves). This overrides the script's own
            # tracker for the trial — the reference's Ray sweep routes trial
            # logging the same way; set tune_config.trial_curves: false to
            # keep the script's tracker instead. The injected plumbing keys
            # stay OUT of the recorded hparams (the record must reproduce
            # the winning config, not this sweep's local paths).
            user_hparams = hparams
            trial_dir = os.path.join(output_dir, f"trial_{i:03d}")
            stats_file = os.path.join(trial_dir, "stats.jsonl")
            if os.path.exists(stats_file):
                # JSONL trackers append, and report() reads this path
                # unconditionally: a rerun into the same output_dir must
                # never fuse (or inherit) a previous run's curves — cleared
                # even when this run injects no tracker
                os.remove(stats_file)
            if trial_curves and "train.tracker" not in hparams:
                hparams = dict(
                    hparams,
                    **{"train.logging_dir": trial_dir, "train.tracker": "jsonl"},
                )
            if host_pool is not None:
                trial_host = host_pool.get()
            elif host_cycle is not None:
                with lock:
                    trial_host = next(host_cycle)
            else:
                trial_host = None
            try:
                rc = run_trial(
                    script,
                    hparams,
                    result_path,
                    log_path,
                    trial_timeout,
                    extra_env,
                    launcher=launcher,
                    host=trial_host,
                    procs_per_trial=procs_per_trial,
                )
            finally:
                if host_pool is not None:
                    host_pool.put(trial_host)
            stats: Dict[str, Any] = {}
            if os.path.exists(result_path):
                with open(result_path) as f:
                    stats = json.load(f)
            record.update(
                hparams=user_hparams,
                u=[float(x) for x in us],
                rc=rc,
                runtime_s=round(time.time() - t0, 1),
                metric=stats.get("stats", {}).get(metric),
                stats=stats.get("stats", {}),
                iter_count=stats.get("iter_count"),
            )
            if rung is not None:
                record["rung"] = rung
            with lock:
                results_f.write(json.dumps(record) + "\n")
                results_f.flush()
            logger.info(
                f"trial {i}{'' if rung is None else f' (rung {rung})'}: rc={rc} "
                f"{metric}={record['metric']} ({record['runtime_s']}s) {hparams}"
            )
            return record

        def launch_batch(
            batch: List[Tuple[Dict[str, Any], np.ndarray, Optional[int]]]
        ) -> List[Dict[str, Any]]:
            """Run a batch of trials, up to ``max_concurrent`` at a time."""
            if max_concurrent <= 1 or len(batch) <= 1:
                return [launch(h, u, r) for h, u, r in batch]
            with ThreadPoolExecutor(max_workers=max_concurrent) as pool:
                futs = [pool.submit(launch, h, u, r) for h, u, r in batch]
                return [f.result() for f in futs]

        def next_us() -> np.ndarray:
            # TPE history: one entry per unit-cube point. ASHA promotions
            # re-launch the same u-vector at a larger budget — keep only the
            # highest-budget (latest-rung) metric per point so promoted
            # configs aren't double-weighted in the Parzen good set, while
            # the search still sees the most-converged estimate.
            by_u: Dict[Tuple[float, ...], Tuple[int, float]] = {}
            with lock:
                snapshot = list(records)
            for r in snapshot:
                if r.get("u") is None or r.get("metric") is None:
                    continue
                key = tuple(r["u"])
                rung = r.get("rung") or 0
                if key not in by_u or rung >= by_u[key][0]:
                    by_u[key] = (rung, sign * r["metric"])
            history = [(list(k), m) for k, (_, m) in by_u.items()]
            return searcher.propose(history)

        def proposals() -> Iterator[Tuple[Dict[str, Any], np.ndarray]]:
            """Lazy (hparams, u) stream: proposed only when consumed, so
            adaptive search sees every completed trial so far. random draws
            fresh coordinates per grid point (full |grid| x num_samples
            coverage); quasirandom keeps one Halton row per draw; TPE
            proposes once per draw — grid dims are marginalized out."""
            for _ in range(draws):
                us = None
                for point in grid_points:
                    if us is None or searcher.alg == "random":
                        us = next_us()
                    yield space.realize(point, us), us

        if scheduler == "fifo":
            # chunks of max_concurrent keep adaptive search fed with
            # completed results between batches
            batch: List[Tuple[Dict[str, Any], np.ndarray, Optional[int]]] = []
            for hparams, us in proposals():
                batch.append((hparams, us, None))
                if len(batch) >= max_concurrent:
                    launch_batch(batch)
                    batch = []
            if batch:
                launch_batch(batch)
        else:
            _run_asha(tune, proposals(), launch_batch, sign, output_dir, max_concurrent)

    def rank_key(r):
        m = r["metric"]
        if m is None:
            return float("inf")
        return -m if mode == "max" else m

    records.sort(key=rank_key)
    report(records, metric, mode, output_dir, wandb_report=wandb_report)
    return records


def _run_asha(
    tune: Dict[str, Any],
    proposals: Iterator[Tuple[Dict[str, Any], np.ndarray]],
    launch_batch,
    sign: float,
    output_dir: str,
    max_concurrent: int = 1,
) -> None:
    """Synchronous successive halving over the trial budget.

    Rung r runs its population with the ``budget_key`` dot-path overridden to
    ``grace_period * reduction_factor**r`` (capped at ``max_t``); the top
    ``1/reduction_factor`` fraction by metric is promoted to the next rung.
    The capability analogue of Ray's HyperBandScheduler in the reference
    (``trlx/sweep.py:136-174``) adapted to subprocess trials.

    By default each config gets a private checkpoint dir
    (``<output_dir>/ckpt_cfg<i>`` via ``train.checkpoint_dir``) and promoted
    trials set ``train.resume_from_checkpoint`` so rung r+1 CONTINUES from
    rung r's final interval checkpoint instead of reburning its compute —
    Ray's pause/resume actor semantics. ``tune_config.asha_resume: false``
    (or custom ``checkpoint_dir_key``/``resume_key``) opts out/retargets.
    """
    eta = int(tune.get("reduction_factor", 3))
    if eta < 2:
        raise ValueError(f"reduction_factor must be >= 2, got {eta}")
    max_t = tune.get("max_t")
    if max_t is None:
        raise ValueError("asha scheduler requires tune_config.max_t (final budget)")
    max_t = int(max_t)
    grace = int(tune.get("grace_period", max(1, max_t // eta**2)))
    budget_key = tune.get("budget_key", "train.total_steps")
    resume = bool(tune.get("asha_resume", True))
    ckpt_key = tune.get("checkpoint_dir_key", "train.checkpoint_dir")
    resume_key = tune.get("resume_key", "train.resume_from_checkpoint")

    def with_ckpt(hparams: Dict[str, Any], cid: int, promoted: bool) -> Dict[str, Any]:
        if not resume:
            return hparams
        hp = dict(hparams)
        hp[ckpt_key] = os.path.join(output_dir, f"ckpt_cfg{cid:03d}")
        if promoted:
            hp[resume_key] = True
        return hp

    t = min(grace, max_t)
    # rung 0 consumes the proposal stream lazily in batches, so adaptive
    # search (bayesopt) sees completed low-budget trials between batches —
    # draining it upfront would silently degrade TPE to its warmup
    results = []
    cid = 0
    pending: List[Tuple[int, Dict[str, Any], np.ndarray]] = []

    def flush_rung0():
        nonlocal results
        if not pending:
            return
        recs = launch_batch(
            [({**with_ckpt(h, c, False), budget_key: t}, us, 0) for c, h, us in pending]
        )
        for (c, h, us), rec in zip(pending, recs):
            if rec["metric"] is not None:
                results.append((sign * rec["metric"], c, h, us))
        pending.clear()

    for hparams, us in proposals:
        pending.append((cid, hparams, us))
        cid += 1
        if len(pending) >= max_concurrent:
            flush_rung0()
    flush_rung0()

    rung = 0
    while t < max_t and results:
        results.sort(key=lambda r: -r[0])
        n_keep = max(1, int(np.ceil(len(results) / eta)))
        survivors = results[:n_keep]
        # a lone survivor jumps straight to the final budget: the winning
        # config always gets its full max_t run
        t = max_t if len(survivors) <= 1 else min(t * eta, max_t)
        rung += 1
        recs = launch_batch(
            [
                ({**with_ckpt(h, c, True), budget_key: t}, us, rung)
                for _, c, h, us in survivors
            ]
        )
        results = [
            (sign * rec["metric"], c, h, us)
            for (_, c, h, us), rec in zip(survivors, recs)
            if rec["metric"] is not None
        ]


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(series: List[float]) -> str:
    finite = [v for v in series if np.isfinite(v)]
    if not finite:
        return ""
    lo, hi = min(finite), max(finite)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[int((v - lo) / span * (len(_SPARK) - 1))] if np.isfinite(v) else " "
        for v in series
    )


def _trial_curve(output_dir: str, trial: int, metric: str) -> List[float]:
    """The trial's metric series from its JSONL tracker stream."""
    path = os.path.join(output_dir, f"trial_{trial:03d}", "stats.jsonl")
    if not os.path.exists(path):
        return []
    series = []
    with open(path) as f:
        for line in f:
            try:
                row = json.loads(line)
                if metric in row:
                    series.append(float(row[metric]))
            except (ValueError, TypeError):
                continue  # a malformed line must not cost the whole report
    return series


def report(
    records: List[Dict[str, Any]],
    metric: str,
    mode: str,
    output_dir: str,
    wandb_report: bool = False,
) -> None:
    """Sweep report: ranked table + per-trial metric curves — the capability
    of the reference's W&B report (``trlx/sweep.py:177-264``, line plots of
    every trial's metric over steps), rendered offline as sparkline rows in
    ``report.md`` with the raw series in ``curves.json``. With
    ``wandb_report=True`` (``tune_config.wandb_report`` — opt-in: an
    unauthenticated ``wandb.init`` blocks on a login prompt, so it must
    never run by surprise) the same curves also publish to a W&B run
    (:func:`publish_wandb_report`)."""
    lines = [f"# Sweep report — {metric} ({mode})", ""]
    lines.append("| rank | trial | " + metric + " | rc | hparams |")
    lines.append("|---|---|---|---|---|")
    for rank, r in enumerate(records):
        lines.append(
            f"| {rank} | {r['trial']} | {r['metric']} | {r['rc']} | `{json.dumps(r['hparams'])}` |"
        )
    best = records[0] if records else None
    if best is not None and best["metric"] is not None:
        lines += ["", f"Best: trial {best['trial']} → {metric}={best['metric']}", f"```json\n{json.dumps(best['hparams'], indent=2)}\n```"]

    curves = {r["trial"]: _trial_curve(output_dir, r["trial"], metric) for r in records}
    if any(curves.values()):
        lines += ["", f"## {metric} over evaluations", ""]
        lines.append("| trial | curve | first | last | n |")
        lines.append("|---|---|---|---|---|")
        for r in records:
            series = curves[r["trial"]]
            if not series:
                continue
            lines.append(
                f"| {r['trial']} | `{_sparkline(series)}` | {series[0]:.4g} "
                f"| {series[-1]:.4g} | {len(series)} |"
            )
        with open(os.path.join(output_dir, "curves.json"), "w") as f:
            json.dump({str(k): v for k, v in curves.items()}, f, indent=2)
    else:
        # a curve-less run must not leave a previous run's curves.json
        # sitting next to a fresh report.md
        stale = os.path.join(output_dir, "curves.json")
        if os.path.exists(stale):
            os.remove(stale)

    text = "\n".join(lines)
    with open(os.path.join(output_dir, "report.md"), "w") as f:
        f.write(text + "\n")
    if logging.get_verbosity() <= logging.INFO:
        print(text)
    if wandb_report:
        publish_wandb_report(records, curves, metric, output_dir)


def publish_wandb_report(
    records: List[Dict[str, Any]],
    curves: Dict[int, List[float]],
    metric: str,
    output_dir: str,
) -> bool:
    """Publish the sweep summary + trial curves as a W&B run (reference
    capability: ``trlx/sweep.py:177-264`` builds a wandb Report of all trial
    charts). Graceful no-op (returns False) when wandb is missing, disabled,
    or offline — the markdown/JSON artifacts above are the offline record."""
    if os.environ.get("WANDB_MODE", "").lower() in ("disabled", "dryrun"):
        return False
    try:
        import wandb
    except ImportError:
        return False
    try:
        run = wandb.init(
            project=os.environ.get("WANDB_PROJECT", "trlx_tpu-sweeps"),
            name=os.path.basename(os.path.abspath(output_dir)),
            job_type="sweep-report",
        )
        table = wandb.Table(columns=["rank", "trial", metric, "hparams"])
        for rank, r in enumerate(records):
            table.add_data(rank, r["trial"], r["metric"], json.dumps(r["hparams"]))
        payload: Dict[str, Any] = {"ranking": table}
        series = [curves[r["trial"]] for r in records if curves.get(r["trial"])]
        if series:
            keys = [f"trial {r['trial']}" for r in records if curves.get(r["trial"])]
            xs = list(range(max(len(s) for s in series)))
            payload["curves"] = wandb.plot.line_series(
                xs=xs, ys=series, keys=keys, title=metric, xname="evaluation"
            )
        run.log(payload)
        run.finish()
        return True
    except Exception as e:  # network/auth problems must never fail the sweep
        logger.warning(f"W&B sweep report skipped: {e}")
        return False


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("script", help="user script exposing main(hparams)")
    parser.add_argument("--config", required=True, help="sweep YAML (dot-path params + tune_config)")
    parser.add_argument("--output-dir", default=None)
    parser.add_argument("--num-samples", type=int, default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--max-concurrent",
        type=int,
        default=1,
        help="run up to N trials at once (requires CPU-mesh trials; see --cpu-trials)",
    )
    parser.add_argument(
        "--cpu-trials",
        action="store_true",
        help="force each trial onto a CPU mesh (JAX_PLATFORMS=cpu) so trials "
        "can run concurrently without contending for the accelerator",
    )
    args = parser.parse_args(argv)

    with open(args.config) as f:
        config = yaml.safe_load(f)
    output_dir = args.output_dir or os.path.join(
        "sweeps", os.path.splitext(os.path.basename(args.script))[0] + time.strftime("-%y%m%d-%H%M%S")
    )
    extra_env = {"JAX_PLATFORMS": "cpu"} if args.cpu_trials else None
    records = run_sweep(
        args.script,
        config,
        output_dir,
        num_samples=args.num_samples,
        seed=args.seed,
        extra_env=extra_env,
        max_concurrent=args.max_concurrent,
    )
    return 0 if records and any(r["metric"] is not None for r in records) else 1


if __name__ == "__main__":
    sys.exit(main())
