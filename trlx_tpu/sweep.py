"""HPO sweep runner: dot-path hyperparameter spaces over a user script.

Capability parity with ``trlx/sweep.py:17-267`` (Ray Tune), rebuilt without a
Ray dependency: trials are subprocesses of the user script (same isolation
property Ray gave the reference — a fresh JAX runtime per trial, no compiled
-program or global-mesh leakage), the search space grammar is identical
(``strategy`` + ``values`` per dot-path key, ``tune_config`` block), and
results aggregate into a JSONL table + ranked report instead of a W&B
report (``trlx/sweep.py:177-264``).

Usage (same CLI shape as the reference)::

    python -m trlx_tpu.sweep --config examples/sweeps/ppo_sweep.yml \
        examples/randomwalks/ppo_randomwalks.py

The user script must expose ``main(hparams: dict)`` (every example does);
each trial invokes ``script.py '<json hparams>'`` with
``TRLX_TPU_SWEEP_RESULT`` pointing at the trial's result file, which the
trainer's learn loop writes at every evaluation (so early-stopped or crashed
trials still report their last metric).

Search algorithms: ``random`` (reference default), ``grid`` (via
``grid`` strategies), and ``quasirandom`` (scrambled Halton — lower
discrepancy coverage than random at small trial counts; beyond the
reference). ``bayesopt``/``bohb`` required external libs in the reference
and are not supported here; ``scheduler`` only accepts ``fifo`` (Ray's
early-stopping schedulers don't map to subprocess trials).
"""

import argparse
import importlib.util
import itertools
import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np
import yaml

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

_PRIMES = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53)


def _halton(index: int, base: int) -> float:
    """Van der Corput radical inverse of ``index`` in ``base`` ∈ (0, 1)."""
    result, f = 0.0, 1.0
    i = index
    while i > 0:
        f /= base
        result += f * (i % base)
        i //= base
    return result


@dataclass
class ParamDef:
    """One swept hyperparameter: a dot-path key + sampling strategy."""

    key: str
    strategy: str
    values: List[Any]

    def sample(self, u: float, rng: np.random.RandomState) -> Any:
        """Draw a value; ``u`` ∈ [0,1) drives continuous strategies (uniform
        or quasirandom position), ``rng`` drives discrete ones."""
        s, v = self.strategy, self.values
        if s == "uniform":
            return float(v[0] + u * (v[1] - v[0]))
        if s == "quniform":
            q = v[2]
            return float(np.round((v[0] + u * (v[1] - v[0])) / q) * q)
        if s == "loguniform":
            lo, hi = np.log(v[0]), np.log(v[1])
            return float(np.exp(lo + u * (hi - lo)))
        if s == "qloguniform":
            lo, hi, q = np.log(v[0]), np.log(v[1]), v[3]
            return float(np.round(np.exp(lo + u * (hi - lo)) / q) * q)
        if s == "randn":
            mean, sd = v
            return float(mean + sd * rng.randn())
        if s == "qrandn":
            mean, sd, q = v
            return float(np.round((mean + sd * rng.randn()) / q) * q)
        if s == "randint":
            return int(v[0] + int(u * (v[1] - v[0])))
        if s == "qrandint":
            q = v[2]
            return int(np.round((v[0] + u * (v[1] - v[0])) / q) * q)
        if s == "lograndint":
            lo, hi = np.log(v[0]), np.log(v[1])
            return int(np.exp(lo + u * (hi - lo)))
        if s == "qlograndint":
            lo, hi, q = np.log(v[0]), np.log(v[1]), v[3]
            return int(np.round(np.exp(lo + u * (hi - lo)) / q) * q)
        if s == "choice":
            return v[rng.randint(len(v))]
        raise ValueError(f"Unknown strategy '{s}' for {self.key}")


@dataclass
class SweepSpace:
    """Parsed sweep config: sampled params + grid params + tune settings."""

    sampled: List[ParamDef] = field(default_factory=list)
    grid: List[ParamDef] = field(default_factory=list)
    tune: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "SweepSpace":
        space = cls()
        for key, value in config.items():
            if key in ("tune_config", "tune"):
                space.tune = dict(value)
                continue
            if not isinstance(value, dict) or "strategy" not in value:
                raise ValueError(
                    f"Sweep entry '{key}' must be a dict with 'strategy' and 'values'"
                )
            pd = ParamDef(key, value["strategy"], value.get("values", []))
            (space.grid if pd.strategy == "grid" else space.sampled).append(pd)
        return space

    def trials(self, num_samples: int, seed: int = 0, search_alg: str = "random") -> Iterator[Dict[str, Any]]:
        """Yield hparam dicts: the cartesian grid × ``num_samples`` draws of
        the sampled params."""
        if search_alg not in ("random", "quasirandom"):
            raise ValueError(
                f"search_alg '{search_alg}' not supported (random, quasirandom; "
                "the reference's bayesopt/bohb need external libs)"
            )
        rng = np.random.RandomState(seed)
        grid_axes = [[(p.key, v) for v in p.values] for p in self.grid] or [[]]
        grid_points = (
            [dict(combo) for combo in itertools.product(*grid_axes)]
            if self.grid
            else [{}]
        )
        draws = max(1, num_samples)
        for i in range(draws):
            for point in grid_points:
                hp = dict(point)
                for j, p in enumerate(self.sampled):
                    if search_alg == "quasirandom":
                        u = _halton(i + 1, _PRIMES[j % len(_PRIMES)])
                    else:
                        u = rng.rand()
                    hp[p.key] = p.sample(u, rng)
                yield hp


def run_trial(
    script: str,
    hparams: Dict[str, Any],
    result_path: str,
    log_path: str,
    timeout: Optional[float] = None,
    extra_env: Optional[Dict[str, str]] = None,
) -> int:
    """One subprocess trial: ``python script.py '<json>'`` with the result
    file advertised via ``TRLX_TPU_SWEEP_RESULT``."""
    env = dict(os.environ)
    env["TRLX_TPU_SWEEP_RESULT"] = result_path
    # trials run with cwd at the script (for its local imports); make this
    # trlx_tpu installation importable there too
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH")) if p
    )
    if extra_env:
        env.update(extra_env)
    with open(log_path, "a") as log:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(script), json.dumps(hparams)],
                cwd=os.path.dirname(os.path.abspath(script)) or None,
                env=env,
                stdout=log,
                stderr=subprocess.STDOUT,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            # a hung trial must not abort the sweep; its last _report_sweep
            # write (if any) still counts
            log.write(f"\nsweep: trial killed after {timeout}s timeout\n")
            return -1
    return proc.returncode


def run_sweep(
    script: str,
    config: Dict[str, Any],
    output_dir: str,
    num_samples: Optional[int] = None,
    seed: int = 0,
    trial_timeout: Optional[float] = None,
    extra_env: Optional[Dict[str, str]] = None,
) -> List[Dict[str, Any]]:
    """Run every trial sequentially (one accelerator — concurrency is
    cross-host, not cross-trial), logging a JSONL results table, and return
    the records ranked best-first."""
    space = SweepSpace.from_config(config)
    tune = space.tune
    metric = tune.get("metric", "reward/mean")
    mode = tune.get("mode", "max")
    n = num_samples or int(tune.get("num_samples", 4))
    search_alg = tune.get("search_alg", "random")
    if tune.get("scheduler", "fifo") != "fifo":
        raise ValueError("Only the fifo scheduler is supported (no Ray trial preemption)")

    os.makedirs(output_dir, exist_ok=True)
    results_path = os.path.join(output_dir, "results.jsonl")
    records: List[Dict[str, Any]] = []
    trials = list(space.trials(n, seed=seed, search_alg=search_alg))
    logger.info(f"Sweep: {len(trials)} trials of {os.path.basename(script)} → {output_dir}")

    with open(results_path, "w") as results_f:
        for i, hparams in enumerate(trials):
            t0 = time.time()
            result_path = os.path.join(output_dir, f"trial_{i:03d}.json")
            log_path = os.path.join(output_dir, f"trial_{i:03d}.log")
            rc = run_trial(script, hparams, result_path, log_path, trial_timeout, extra_env)
            stats: Dict[str, Any] = {}
            if os.path.exists(result_path):
                with open(result_path) as f:
                    stats = json.load(f)
            record = {
                "trial": i,
                "hparams": hparams,
                "rc": rc,
                "runtime_s": round(time.time() - t0, 1),
                "metric": stats.get("stats", {}).get(metric),
                "stats": stats.get("stats", {}),
                "iter_count": stats.get("iter_count"),
            }
            records.append(record)
            results_f.write(json.dumps(record) + "\n")
            results_f.flush()
            logger.info(
                f"trial {i}: rc={rc} {metric}={record['metric']} "
                f"({record['runtime_s']}s) {hparams}"
            )

    def rank_key(r):
        m = r["metric"]
        if m is None:
            return float("inf")
        return -m if mode == "max" else m

    records.sort(key=rank_key)
    report(records, metric, mode, output_dir)
    return records


def report(records: List[Dict[str, Any]], metric: str, mode: str, output_dir: str) -> None:
    """Ranked text report (the reference renders a W&B report,
    ``trlx/sweep.py:177-264``; offline JSONL + markdown table here)."""
    lines = [f"# Sweep report — {metric} ({mode})", ""]
    lines.append("| rank | trial | " + metric + " | rc | hparams |")
    lines.append("|---|---|---|---|---|")
    for rank, r in enumerate(records):
        lines.append(
            f"| {rank} | {r['trial']} | {r['metric']} | {r['rc']} | `{json.dumps(r['hparams'])}` |"
        )
    best = records[0] if records else None
    if best is not None and best["metric"] is not None:
        lines += ["", f"Best: trial {best['trial']} → {metric}={best['metric']}", f"```json\n{json.dumps(best['hparams'], indent=2)}\n```"]
    text = "\n".join(lines)
    with open(os.path.join(output_dir, "report.md"), "w") as f:
        f.write(text + "\n")
    if logging.get_verbosity() <= logging.INFO:
        print(text)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("script", help="user script exposing main(hparams)")
    parser.add_argument("--config", required=True, help="sweep YAML (dot-path params + tune_config)")
    parser.add_argument("--output-dir", default=None)
    parser.add_argument("--num-samples", type=int, default=None)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    with open(args.config) as f:
        config = yaml.safe_load(f)
    output_dir = args.output_dir or os.path.join(
        "sweeps", os.path.splitext(os.path.basename(args.script))[0] + time.strftime("-%y%m%d-%H%M%S")
    )
    records = run_sweep(
        args.script, config, output_dir, num_samples=args.num_samples, seed=args.seed
    )
    return 0 if records and any(r["metric"] is not None for r in records) else 1


if __name__ == "__main__":
    sys.exit(main())
