"""Path-based parameter sharding rules (t5x/maxtext style).

One ordered rule table maps every parameter path in the model tree to a
``PartitionSpec`` over the ``(data, pipe, fsdp, model, sequence, expert)``
mesh:

- the **model** axis carries Megatron-style tensor parallelism — qkv/mlp-up
  kernels shard their *output* features, o/mlp-down kernels their *input*
  features, embeddings and lm head shard the vocab dim (the reference gets
  this from Apex ``ColumnParallelLinear``/``RowParallelLinear``,
  ``trlx/models/modeling_nemo_ilql.py:47-99``);
- the **fsdp** axis shards the remaining large dim of each kernel — the GSPMD
  equivalent of DeepSpeed ZeRO-3 parameter sharding
  (``configs/accelerate/zero3.yaml``), with XLA inserting the all-gathers;
- small tensors (norms, biases of row-parallel layers) replicate.

Rules apply to *paths*, so the same table covers the backbone, value heads,
Q heads, and any future module that follows the naming convention.
"""

import functools
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Ordered (path regex, spec) rules; first match wins. Paths are joined with
# "/" and include every key from the root of the param tree.
_RULES: Tuple[Tuple[str, P], ...] = (
    # attention + mlp column-parallel (output features on `model`)
    (r".*/(q_proj|k_proj|v_proj|gate_proj|up_proj)/kernel$", P("fsdp", "model")),
    (r".*/(q_proj|k_proj|v_proj|gate_proj|up_proj)/bias$", P("model")),
    # row-parallel (input features on `model`); bias replicated
    (r".*/(o_proj|down_proj)/kernel$", P("model", "fsdp")),
    (r".*/(o_proj|down_proj)/bias$", P(None)),
    # mixture-of-experts MLP: expert dim over `expert` (EP), per-expert
    # matmul dims over fsdp/model exactly like the dense column/row split;
    # the router is tiny and replicates
    (r".*/mlp/w_(gate|up)$", P("expert", "fsdp", "model")),
    (r".*/mlp/w_down$", P("expert", "model", "fsdp")),
    (r".*/mlp/router/kernel$", P(None)),
    # vocab-parallel embedding (Megatron-style: vocab over model×fsdp, embed
    # replicated — lookups then yield cleanly batch-sharded activations; an
    # embed-dim-sharded table instead forces a GSPMD involuntary
    # replicate-and-repartition on every lookup output). Deliberate
    # trade-off: when the vocab doesn't divide the axes (gpt2's prime-ish
    # 50257) the table replicates rather than falling back to embed-dim
    # sharding — the indivisible-vocab families top out ~1.5B params
    # (≤0.5GB table), where replication is cheap and the lookup-layout win
    # is measured; every 6B+ family (llama/neox/bloom/opt/gptj) divides.
    (r".*/wte/embedding$", P(("model", "fsdp"), None)),
    (r".*/wpe/embedding$", P(None, None)),
    (r".*/lm_head/kernel$", P("fsdp", "model")),
    (r".*/lm_head/bias$", P("model")),
    # MLP heads (value / Q): column-parallel in, row-parallel out
    (r".*/in_proj/kernel$", P("fsdp", "model")),
    (r".*/in_proj/bias$", P("model")),
    (r".*/out_proj/kernel$", P("model", None)),
    (r".*/out_proj/bias$", P(None)),
    # everything else (norm scales/biases, odd singletons): replicated
    (r".*", P()),
)


def _axis_size(mesh: Mesh, name) -> int:
    if name is None:
        return 1
    if isinstance(name, tuple):  # combined axes, e.g. ("model", "fsdp")
        size = 1
        for axis in name:
            size *= mesh.shape[axis]
        return size
    return mesh.shape[name]


def param_spec_for_path(
    path: str, shape: Tuple[int, ...], mesh: Optional[Mesh] = None
) -> P:
    """Resolve the PartitionSpec for a parameter path.

    With a ``mesh``, each dim keeps the longest prefix of its axis group that
    divides it (:func:`fit_spec`) — e.g. a 50257 vocab over ``('model',
    'fsdp')`` replicates (odd vocab), while a vocab divisible by ``model``
    but not ``model×fsdp`` still shards over ``model`` — so sharding never
    fails on awkward dims and XLA still shards everything that divides.
    """
    for pattern, spec in _RULES:
        if re.match(pattern, path):
            break
    partitions = tuple(spec)
    if "/h_scan/" in path or path.startswith("h_scan/"):
        # scan_layers layout: a leading layer dim precedes every rule's dims
        # (stacked blocks); the layer axis shards over `pipe` — with PP>1
        # each stage's devices hold only their own blocks (the reference's
        # per-stage Megatron partitions, ``modeling_nemo_ilql.py:219-250``);
        # at pipe=1 the axis is size 1 and the spec is a no-op
        partitions = ("pipe",) + partitions
    partitions = partitions[: len(shape)]
    if mesh is not None:
        fitted = fit_spec(mesh, shape, partitions)
        # diagnosis for silently-replicated LARGE params: a dim that sheds
        # its whole (present, >1-sized) axis group costs real memory —
        # activation constraints go through fit_spec directly and stay
        # silent (there a dropped group just skips the constraint)
        if int(np.prod(shape)) * 4 >= _REPLICATE_WARN_BYTES:
            for dim, axis, kept in zip(shape, partitions, tuple(fitted)):
                if axis is None or kept is not None:
                    continue
                names = axis if isinstance(axis, tuple) else (axis,)
                present = tuple(n for n in names if n in mesh.shape)
                group = 1
                for n in present:
                    group *= mesh.shape[n]
                if group > 1:
                    _warn_dropped_axis_group(path, tuple(shape), dim, present, group)
        return fitted
    partitions = partitions + (None,) * (len(shape) - len(partitions))
    return P(*partitions)


def path_keys(key_path) -> Tuple[str, ...]:
    """jax key-path → tuple of key strings (shared by the rule matcher here
    and the structural optimizer-state matcher in ``trainer/base.py``)."""
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return tuple(parts)


def _path_str(key_path) -> str:
    return "/".join(path_keys(key_path))


def param_specs(params: Any, mesh: Optional[Mesh] = None) -> Any:
    """PartitionSpec pytree matching ``params``."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = [
        param_spec_for_path(_path_str(key_path), np.shape(leaf), mesh)
        for key_path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, specs)


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding pytree matching ``params``."""
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(params, mesh),
        is_leaf=lambda x: isinstance(x, P),
    )


def _stage_global(
    x: Any, sharding: NamedSharding, staged: list, reland: bool = False
) -> jax.Array:
    """One leaf of :func:`put_global`: ``device_put`` when fully addressable,
    else the callback path with the staged (host-provenance) buffer appended
    to ``staged`` for the caller's :func:`_land_staged` sync+delete.

    ``reland=True`` forces the copy protocol on the fully-addressable branch
    too: CPU ``device_put`` of a host numpy array can alias the host buffer
    zero-copy, and a leaf that will be DONATED into a cached executable must
    be a fresh XLA-owned buffer (the restore heap-corruption hazard —
    ``utils/checkpoint.py::restore_state``). Plain placement (params built
    on device, non-donated batches) skips the copy.

    Multihost ``jax.device_put`` of host data onto a non-fully-addressable
    sharding inserts a cross-process value-equality check implemented as a
    psum — which the CPU collective backend rejects, and which is redundant
    here: every caller places host values all processes computed
    identically (SPMD host code, same seed/config). The callback path
    assembles each process's addressable shards directly — no collective,
    identical result, and the single-process behavior stays plain
    ``device_put``."""
    import jax.numpy as jnp

    if sharding.is_fully_addressable:
        out = jax.device_put(x, sharding)
        if not reland:
            return out
        staged.append(out)
        return jnp.copy(out)

    arr = np.asarray(x)
    buf = jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])
    # callback buffers are host-provenance: donated into an executable
    # deserialized from the persistent compile cache they corrupt the heap
    # (the hazard utils/checkpoint.py::restore_state and resilience/
    # elastic.py re-land against). shard_params output IS donated into the
    # train step, so re-land here too; the copy is placement-time cost for
    # params and a minor per-batch cost for multihost shard_batch.
    staged.append(buf)
    return jnp.copy(buf)


def _land_staged(out: Any, staged: list) -> None:
    """ONE device sync for a whole placed tree, then free the staged
    buffers. The copies must have landed before their sources are deleted,
    but syncing per leaf would serialize transfers the runtime pipelines —
    a k-leaf batch pays one barrier, not k (non-array leaves in ``out`` are
    ignored by ``jax.block_until_ready``)."""
    if staged:
        jax.block_until_ready(out)
        for buf in staged:
            buf.delete()


def put_global(x: Any, sharding: NamedSharding, reland: bool = False) -> jax.Array:
    """``device_put`` that also works when ``sharding`` spans processes
    (see :func:`_stage_global`; ``reland`` for leaves headed into donating
    executables). Single-leaf entry — tree placement goes through
    :func:`shard_params`/:func:`shard_batch`, which batch the device sync
    across leaves."""
    staged: list = []
    out = _stage_global(x, sharding, staged, reland=reland)
    _land_staged(out, staged)
    return out


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place a parameter pytree onto the mesh per the rule table."""
    staged: list = []
    out = jax.tree_util.tree_map(
        lambda x, s: _stage_global(x, s, staged), params, param_shardings(params, mesh)
    )
    _land_staged(out, staged)
    return out


# Params at or above this size (bytes, assuming 4 B/element — specs see only
# shapes, not dtypes) get a diagnosis line when a dim sheds its entire axis
# group; smaller ones replicate silently (cheap and usually deliberate).
# Scoped to *params* (``param_spec_for_path``): for activation constraints
# the same drop means the constraint is skipped to preserve layout freedom
# (``constrain_activation``'s no-op path), not that anything replicates.
_REPLICATE_WARN_BYTES = 8 << 20


@functools.lru_cache(maxsize=None)
def _warn_dropped_axis_group(path, shape, dim, names, group) -> None:
    """Warn ONCE per (param, shape, axes) signature: the divisibility fit
    silently drops *every* axis of the group, so a large param replicates —
    up to ``group``× the memory and none of the sharding the rule table
    intended. Same warn-once contract as
    ``models/transformer.py::_warn_indivisible_experts``."""
    from trlx_tpu.utils import logging

    logging.get_logger(__name__).warning(
        "param %s of shape %s (>= %d MiB assuming 4 B/elem): the %d-sized dim "
        "is divisible by no prefix of mesh axes %s (combined size %d) — the "
        "dim replicates instead of sharding; resize the dim or the mesh axes "
        "to recover it",
        path, shape, _REPLICATE_WARN_BYTES >> 20, dim, names, group,
    )


def fit_spec(mesh: Mesh, shape: Tuple[int, ...], spec: Tuple[Any, ...]) -> P:
    """Fit a PartitionSpec to a concrete shape: per dim, keep the longest
    prefix of the axis group whose product divides the dim (``None`` when no
    present axis divides).

    Sharding constraints written for the general case meet awkward concrete
    dims — a microbatch of 1, a 6-wide head dim on a 4-way axis group. Padding
    a dim onto an axis it doesn't divide gives every consumer a
    differently-padded layout, and each reshard between them becomes a GSPMD
    involuntary full rematerialization; dropping just the non-dividing suffix
    keeps whatever sharding still fits. Size-1 axes that divide are KEPT —
    they are sharding no-ops, but retaining them keeps specs stable across
    mesh sizes (the rule table reads the same at pipe=1 and pipe=4).
    """
    if len(spec) > len(shape):
        raise ValueError(
            f"spec {tuple(spec)} has more entries than array rank {len(shape)}"
        )
    out = []
    for dim, axis in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axis is None:
            out.append(None)
            continue
        names = axis if isinstance(axis, tuple) else (axis,)
        keep: list = []
        size = 1
        for n in names:
            if n not in mesh.shape:
                continue  # absent axis contributes size 1 — skip, don't emit
            s = mesh.shape[n]
            if dim % (size * s):
                break
            keep.append(n)
            size *= s
        if keep:
            out.append(tuple(keep) if len(keep) > 1 else keep[0])
        else:
            out.append(None)
    return P(*out)


def spec_to_jsonable(spec: P) -> list:
    """A PartitionSpec as JSON-safe nested lists (``None`` | axis name |
    list of names per dim) — the checkpoint topology manifest's per-leaf
    spec record (``trlx_tpu/resilience/elastic.py``)."""
    out = []
    for axis in tuple(spec):
        if axis is None:
            out.append(None)
        elif isinstance(axis, tuple):
            out.append([str(a) for a in axis])
        else:
            out.append(str(axis))
    return out


def spec_shards(mesh: Mesh, spec: P) -> int:
    """Total ways ``spec`` splits an array on ``mesh`` (1 = pure no-op)."""
    total = 1
    for axis in spec:
        total *= _axis_size(mesh, axis)
    return total


def constrain_activation(a: jax.Array, mesh: Optional[Mesh], *spec) -> jax.Array:
    """``with_sharding_constraint`` with the :func:`fit_spec` guard — the one
    helper behind every activation-layout pin (decode embedding, pipeline
    feed/drain streams, MoE dispatch). No-op without a mesh or when the
    fitted spec shards nothing (a no-op constraint would still force full
    replication rather than preserve layout freedom)."""
    if mesh is None:
        return a
    fitted = fit_spec(mesh, a.shape, spec)
    if spec_shards(mesh, fitted) == 1:
        return a
    return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, fitted))


def batch_spec(ndim: int = 2, sequence_sharded: bool = False) -> P:
    """Batch arrays shard their leading dim over the combined data axes
    (``data`` × ``fsdp`` — FSDP is data parallelism with sharded state);
    optionally the second (sequence) dim over ``sequence``."""
    rest: Tuple[Optional[str], ...] = ("sequence",) if sequence_sharded else (None,)
    rest = rest + (None,) * (ndim - 2)
    return P(("data", "fsdp"), *rest[: max(ndim - 1, 0)])


def shard_batch(batch: Any, mesh: Mesh, sequence_sharded: bool = False) -> Any:
    """Place host batch arrays (numpy) onto the mesh, sharded over data axes.

    Leading dims must be divisible by ``data*fsdp`` (collators guarantee this
    by construction: batch sizes are multiples of the data-axes product).
    Non-array leaves (strings etc.) pass through untouched.
    """

    staged: list = []

    def put(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        dp = mesh.shape["data"] * mesh.shape["fsdp"]
        if x.shape[0] % dp != 0:
            spec = P()
        else:
            spec = batch_spec(x.ndim, sequence_sharded)
        return _stage_global(x, NamedSharding(mesh, spec), staged)

    out = jax.tree_util.tree_map(put, batch)
    _land_staged(out, staged)
    return out
