"""Device mesh construction.

Axes:

- ``data``     — pure data parallelism (the reference's DDP replicas,
  ``configs/accelerate/ddp.yaml``). Across slices/hosts this axis rides DCN.
- ``fsdp``     — parameter/optimizer-state sharding (the reference's DeepSpeed
  ZeRO-2/3, ``configs/accelerate/zero*.yaml``). Also acts as a data axis for
  the batch: FSDP = DP + sharded state.
- ``pipe``     — pipeline-parallel stages (the reference's Apex/Megatron
  pipeline engine, ``trlx/models/modeling_nemo_ilql.py:426-442``). Placed
  outside model/sequence in the axis order: stage handoffs move one
  microbatch of activations per tick (low bandwidth), so they can ride the
  slower links while TP/ring collectives keep the fastest ICI.
- ``model``    — tensor parallelism (the reference's Megatron TP,
  ``configs/nemo_configs/megatron_20b.yaml:53``).
- ``sequence`` — context parallelism for long sequences (ring attention);
  beyond the reference, which only has Megatron SP inside TP.
- ``expert``   — expert parallelism for mixture-of-experts MLPs (mixtral
  family): expert weights shard their leading expert dim here and GSPMD
  lowers the dispatch/combine einsums to all_to_alls over this axis. Beyond
  the reference (SURVEY.md §2.3: EP absent).

The mesh is the single source of truth for every compiled program: train
steps, rollout decode, and eval all run under the same mesh so arrays never
leave the device fabric between phases.
"""

import math
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from trlx_tpu.data.configs import ParallelConfig

MESH_AXES = ("data", "pipe", "fsdp", "model", "sequence", "expert")

# The process-wide mesh, set by trainers at construction. Model code reads it
# (``get_global_mesh``) to decide whether sequence-parallel ops (ring
# attention) apply — the mesh, not per-module config, is the single source of
# truth for parallelism.
_GLOBAL_MESH: Optional[Mesh] = None


def set_global_mesh(mesh: Optional[Mesh]) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH


def mesh_descriptor(mesh: Mesh) -> dict:
    """JSON-serializable identity of a mesh — the fields the elastic
    checkpoint manifest compares to decide whether a restore crosses a
    topology change (``trlx_tpu/resilience/elastic.py``). Axis names and
    sizes plus the process/device counts pin the placement; device ids are
    deliberately excluded (the same topology on different physical chips —
    a rescheduled pod — must compare equal)."""
    devices = np.asarray(mesh.devices).ravel()
    return {
        "axes": [str(a) for a in mesh.axis_names],
        "shape": [int(mesh.shape[a]) for a in mesh.axis_names],
        "device_count": int(devices.size),
        "process_count": len({d.process_index for d in devices}),
        "platform": str(getattr(devices[0], "platform", "unknown")),
    }


def local_mesh(parallel: Optional[ParallelConfig] = None) -> Mesh:
    """A mesh over THIS process's local devices only — the actor-slice
    mesh of a disaggregated fleet (Podracer's learner/actor mesh pairs,
    arXiv 2104.06272; RLAX's actor slices, arXiv 2512.06392).

    A fleet member's compiled programs (generation, scoring) must never
    span another member's devices: learner and actors run *different*
    programs concurrently, so a global mesh would deadlock the first time
    one side launched a collective the other never posts. Each member
    therefore builds its mesh from ``jax.local_devices()``; the host-side
    fleet fabric (``async_rl/transport.py``) carries params and experience
    *between* the per-member meshes. In single-runtime deployments (every
    process its own JAX world — today's CPU harness) local and global
    devices coincide and this is simply :func:`make_mesh`; in a shared
    ``jax.distributed`` world it is the actor's slice carved out of the
    pod. The member advertises ``mesh_descriptor(local_mesh())`` in its
    fleet HELLO, so the coordinator can log the fleet's topology."""
    import jax

    return make_mesh(parallel, devices=jax.local_devices())


def mesh_shape_from_config(
    parallel: ParallelConfig, device_count: Optional[int] = None
) -> Tuple[int, int, int, int, int, int]:
    """Resolve the 6-axis mesh shape; a single ``-1`` axis is inferred."""
    n = device_count if device_count is not None else jax.device_count()
    sizes = [
        parallel.data,
        parallel.pipe,
        parallel.fsdp,
        parallel.model,
        parallel.sequence,
        parallel.expert,
    ]
    if sizes.count(-1) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {sizes}")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n % known != 0:
            raise ValueError(
                f"Device count {n} not divisible by fixed axes product {known}"
            )
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(
            f"Mesh {dict(zip(MESH_AXES, sizes))} needs {math.prod(sizes)} devices, "
            f"have {n}"
        )
    return tuple(sizes)


def make_mesh(
    parallel: Optional[ParallelConfig] = None, devices=None
) -> Mesh:
    """Build the global ``Mesh`` from a :class:`ParallelConfig`.

    Multi-host TPU: with ``dcn_data_parallelism > 1`` the data axis is laid
    out hierarchically (slow DCN links only carry the pure-DP axis; fsdp/
    model/sequence collectives stay on ICI within a slice) via
    ``mesh_utils.create_hybrid_device_mesh``. Single-slice: a contiguous
    ``create_device_mesh`` keeps the model axis on adjacent chips, which is
    the layout the Megatron TP pattern expects of NVLink in the reference.
    """
    parallel = parallel or ParallelConfig()
    devices = devices if devices is not None else jax.devices()
    shape = mesh_shape_from_config(parallel, len(devices))

    if parallel.dcn_data_parallelism > 1:
        from jax.experimental import mesh_utils

        dcn = parallel.dcn_data_parallelism
        if shape[0] % dcn != 0:
            raise ValueError(
                f"data axis {shape[0]} not divisible by dcn_data_parallelism {dcn}"
            )
        ici_shape = (shape[0] // dcn,) + shape[1:]
        dcn_shape = (dcn,) + (1,) * (len(shape) - 1)
        device_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices
        )
        return Mesh(device_array, MESH_AXES)

    try:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        # CPU/host platforms have no topology info; plain reshape
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)
