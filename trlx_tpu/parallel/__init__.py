"""Parallelism: device mesh + GSPMD sharding rules.

The TPU-native replacement for the reference's *three* distributed stacks
(Accelerate/DeepSpeed ZeRO, ``configs/accelerate/*.yaml``; NeMo Megatron
TP/PP/SP, ``trlx/models/modeling_nemo_ilql.py``; raw torch.distributed/NCCL
calls, ``trlx/utils/modeling.py:190-202``): one logical program over a
``jax.sharding.Mesh`` with axes ``(data, pipe, fsdp, model, sequence)``. XLA inserts
the collectives (all-gather / reduce-scatter / psum) over ICI/DCN — no
hand-written communication.
"""

from trlx_tpu.parallel.mesh import (
    get_global_mesh,
    make_mesh,
    mesh_shape_from_config,
    set_global_mesh,
)
from trlx_tpu.parallel.sharding import (
    batch_spec,
    param_shardings,
    param_spec_for_path,
    shard_batch,
    shard_params,
)

__all__ = [
    "get_global_mesh",
    "set_global_mesh",
    "make_mesh",
    "mesh_shape_from_config",
    "param_shardings",
    "param_spec_for_path",
    "batch_spec",
    "shard_batch",
    "shard_params",
]
