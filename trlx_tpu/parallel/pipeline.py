"""GPipe-style pipeline parallelism over stacked transformer blocks.

The reference's pipeline engine is Apex/Megatron: layers are partitioned
across PP ranks, a microbatch schedule (``fwd_bwd_function``) sends stage
activations over NCCL p2p, heads live on the last stage
(``trlx/models/modeling_nemo_ilql.py:339-366,426-442``; PP=4 for 65B,
``configs/nemo_configs/megatron_65b.yaml:50``). The TPU-native equivalent
here is the GSPMD pipelining pattern (vmap-over-stages + rotating microbatch
buffer, as in the GSPMD paper §3.3 / praxis ``LayerwiseShardablePipelined``):

- the ``scan_layers`` stacked block params ``[L, ...]`` shard their layer dim
  over the mesh's ``pipe`` axis, so each stage's devices hold only their own
  ``L/S`` blocks (the analogue of Megatron's per-rank partitions);
- one jitted program runs ``M + S - 1`` schedule ticks as a ``lax.scan``;
  each tick every stage applies its blocks to the microbatch currently
  resident on it (a ``vmap`` over the stage dim — SPMD, so all stages
  compute every tick), then the activation buffer shifts one stage down via
  ``concatenate`` along the stage dim, which XLA lowers to a collective
  permute over ``pipe`` — the NCCL send/recv of the reference, compiler-
  inserted;
- microbatches enter at stage 0 and exit at stage ``S-1``; ticks before the
  pipeline fills / after it drains process replicated filler data whose
  results are discarded (the GPipe bubble — ``(S-1)/(M+S-1)`` of the
  schedule, amortised by raising ``num_microbatches``).

Deviations from the reference, by design: embeddings and the LM/value heads
are *not* stage-local — they stay sharded over ``model``/``fsdp`` and
replicated over ``pipe`` (GSPMD places their FLOPs on all devices), so there
is no first/last-stage embedding allreduce (``modeling_nemo_ilql.py:475-477``)
and no loss broadcast from the last stage (``:479-481``): outputs exit the
pipeline globally addressable, and backward is plain autodiff through the
schedule (XLA reverses the collective permutes). KV-cache decode runs through
the same schedule with stage-resident caches and validity-guarded writes.
"""

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh


def pick_microbatches(batch_size: int, num_stages: int, requested: int = 0) -> int:
    """Resolve the microbatch count: ``requested`` (0 = one per stage), capped
    at the batch size, reduced to the largest divisor of the batch. Warns when
    the divisor fallback inflates the pipeline bubble (``(S-1)/(M+S-1)`` of
    the schedule is filler) so a throughput cliff is diagnosable."""
    target = min(requested if requested > 0 else num_stages, batch_size)
    m = target
    while batch_size % m:
        m -= 1
    if m < target:
        from trlx_tpu.utils import logging

        logging.get_logger(__name__).warning(
            "pipe microbatches reduced %d -> %d (largest divisor of batch %d): "
            "pipeline bubble is now %d/%d of the schedule — pick a batch size "
            "divisible by the microbatch count to recover throughput",
            target, m, batch_size, num_stages - 1, m + num_stages - 1,
        )
    return m


class _TickCarry(NamedTuple):
    h: jax.Array  # [S, mb, T, E] stage-resident activations
    mask: jax.Array  # [S, mb, K] attention/slot mask riding with its microbatch
    positions: jax.Array  # [S, mb, T]
    branch: Any  # [S, mb, T, E] hydra branch-input buffer, or None
    cache: Any  # stage-resident KV cache pytree, or None


def _shift_in(buf: jax.Array, inject: jax.Array) -> jax.Array:
    """Rotate the stage buffer one stage down, injecting ``inject`` at stage
    0. The cross-stage concatenate is what XLA turns into the pipe-axis
    collective permute."""
    return jnp.concatenate([inject[None], buf[:-1]], axis=0)


def pipeline_blocks(
    stacked_params: Any,  # pytree, leaves [L, ...] (the h_scan/block stack)
    x: jax.Array,  # [B, T, E]
    mask: jax.Array,  # [B, K] key/slot mask (K == T full pass; cache slots in decode)
    positions: jax.Array,  # [B, T]
    *,
    num_stages: int,
    num_microbatches: int,
    # (mask_mb, pos_mb, cache_index_mb) -> attn inputs for one microbatch;
    # cache_index_mb is the stage's [mb] slice when cache_index is a [B]
    # vector (speculative decoding), else the scalar/None passed in
    make_attn_inputs: Callable[..., Any],
    # (layer_params, h, attn_inputs, cache_layer, cache_index_mb)
    #   -> (h, new_cache_layer, aux_stats)
    apply_block: Callable[..., Tuple[jax.Array, Any, jax.Array]],
    cache: Any = None,  # pytree, leaves [L, B, ...] (stacked KV cache) or None
    cache_index: Any = None,  # None | scalar | [B] vector (per-row depths)
    branch_at: int = -1,  # global layer idx whose INPUT feeds the hydra branch
    mesh: Optional[Mesh] = None,
    aux_init: Optional[jax.Array] = None,  # zero aux vector (defines its width)
) -> Tuple[jax.Array, Optional[jax.Array], Any, jax.Array]:
    """Run the stacked block params over ``x`` through the pipeline schedule.

    Returns ``(hidden, branch_input, new_cache, aux)`` — hidden/branch/cache
    with the same shapes/layout the unpipelined ``nn.scan`` path produces
    (tested for exact logits parity). ``aux`` is the raw SUM of each block's
    aux-statistics vector over every valid (layer, microbatch) pair: blocks
    return token-weighted sufficient statistics (see
    ``models/transformer.py::router_aux_summary``), so the caller's final
    normalization stays correctly weighted even when microbatches carry
    different amounts of padding.
    """
    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    S, M = num_stages, num_microbatches
    if L % S:
        raise ValueError(f"num_layers {L} not divisible by pipe stages {S}")
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by pipe microbatches {M}")
    lps, mb = L // S, B // M
    track_branch = branch_at >= 0
    if aux_init is None:
        aux_init = jnp.zeros(3, jnp.float32)

    # [L, ...] -> [S, lps, ...]: L is sharded over `pipe` with exactly lps
    # contiguous rows per shard, so this reshape is local to each device.
    params_s = jax.tree_util.tree_map(
        lambda p: p.reshape((S, lps) + p.shape[1:]), stacked_params
    )
    split = lambda a: a.reshape((M, mb) + a.shape[1:])
    # pad the input streams to M + S - 1 ticks with replicas of microbatch 0:
    # real data (no NaN hazards), results discarded by the schedule
    tk = M + S - 1
    feed = lambda a: jnp.concatenate([a, jnp.repeat(a[:1], tk - M, axis=0)], axis=0)
    xs, masks, poss = feed(split(x)), feed(split(mask)), feed(split(positions))

    cache_s = None
    if cache is not None:
        # [L, B, ...] -> [S, lps, M, mb, ...]: stage-resident, never rotated
        cache_s = jax.tree_util.tree_map(
            lambda c: c.reshape((S, lps, M, mb) + c.shape[2:]), cache
        )

    # a [B]-vector cache_index (per-row cache depths — speculative decoding)
    # is split per microbatch like the data streams; each stage selects its
    # resident microbatch's slice by m_idx, exactly as it selects the cache
    vector_ci = cache_index is not None and jnp.ndim(cache_index) > 0
    ci_split = split(jnp.asarray(cache_index)) if vector_ci else None  # [M, mb]

    def constrain(a, *spec):
        if not isinstance(a, jax.core.Tracer):
            return a
        from trlx_tpu.parallel.sharding import constrain_activation

        return constrain_activation(a, mesh, *spec)

    # the microbatch streams are sliced per tick and injected into the
    # [S, mb, ...] stage buffer (dim1 over data×fsdp); constraining them here,
    # once, hands every per-tick slice to the buffer in its final layout —
    # otherwise the split()-reshape of the batch-sharded input leaves the
    # slices in a transposed device order the partitioner can only reconcile
    # with an involuntary full rematerialization at each injection
    xs = constrain(xs, None, ("data", "fsdp"))
    masks = constrain(masks, None, ("data", "fsdp"))
    poss = constrain(poss, None, ("data", "fsdp"))

    def stage_fn(stage_params, h, mask_mb, pos_mb, branch_buf, stage_cache, m_idx, stage_idx, valid):
        """One stage: apply its ``lps`` blocks to the resident microbatch."""
        ci = cache_index
        if vector_ci:
            ci = jax.lax.dynamic_index_in_dim(ci_split, m_idx, axis=0, keepdims=False)
        aux = make_attn_inputs(mask_mb, pos_mb, ci)
        cache_m = None
        if stage_cache is not None:
            # this stage currently serves microbatch m_idx: select its cache
            cache_m = jax.tree_util.tree_map(
                lambda c: jax.lax.dynamic_index_in_dim(c, m_idx, axis=1, keepdims=False),
                stage_cache,
            )

        def layer_body(carry, inp):
            h, branch_buf, aux_sum = carry
            layer_params, cache_layer, local_idx = inp
            if track_branch:
                branch_buf = jnp.where(
                    stage_idx * lps + local_idx == branch_at, h, branch_buf
                )
            h, new_cache_layer, block_aux = apply_block(
                layer_params, h, aux, cache_layer, ci
            )
            return (h, branch_buf, aux_sum + block_aux), new_cache_layer

        (h, branch_buf, aux_sum), new_cache_m = jax.lax.scan(
            layer_body,
            (h, branch_buf, aux_init),
            (stage_params, cache_m, jnp.arange(lps)),
        )
        new_stage_cache = None
        if stage_cache is not None:
            # commit the updated cache only when this stage held real data
            updated = jax.tree_util.tree_map(
                lambda c, n: jax.lax.dynamic_update_index_in_dim(c, n, m_idx, axis=1),
                stage_cache,
                new_cache_m,
            )
            new_stage_cache = jax.tree_util.tree_map(
                lambda u, c: jnp.where(valid, u, c), updated, stage_cache
            )
        return h, branch_buf, new_stage_cache, aux_sum

    stages = jax.vmap(stage_fn, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0))
    stage_iota = jnp.arange(S)

    def tick(carry: _TickCarry, inputs):
        x_t, mask_t, pos_t, t = inputs
        h = constrain(_shift_in(carry.h, x_t), "pipe", ("data", "fsdp"))
        mk = constrain(_shift_in(carry.mask, mask_t), "pipe", ("data", "fsdp"))
        ps = constrain(_shift_in(carry.positions, pos_t), "pipe", ("data", "fsdp"))
        br = carry.branch
        if track_branch:
            br = constrain(
                _shift_in(br, jnp.zeros_like(x_t)), "pipe", ("data", "fsdp")
            )
        # stage s serves microbatch t - s (valid while 0 <= t-s < M)
        m = t - stage_iota
        valid = (m >= 0) & (m < M)
        m_idx = jnp.clip(m, 0, M - 1)
        h, br, cache_new, aux_s = stages(
            params_s, h, mk, ps, br, carry.cache, m_idx, stage_iota, valid
        )
        h = constrain(h, "pipe", ("data", "fsdp"))
        # filler ticks (invalid stage/microbatch pairs) must not contribute
        aux_t = jnp.sum(jnp.where(valid[:, None], aux_s, 0.0), axis=0)
        out = (h[-1], br[-1] if track_branch else jnp.zeros((0,), x.dtype), aux_t)
        return _TickCarry(h, mk, ps, br, cache_new), out

    zeros_buf = jnp.zeros((S, mb) + x.shape[1:], x.dtype)
    init = _TickCarry(
        h=zeros_buf,
        # all-ones masks keep the filler ticks numerically benign
        mask=jnp.ones((S, mb) + mask.shape[1:], mask.dtype),
        positions=jnp.zeros((S, mb) + positions.shape[1:], positions.dtype),
        branch=zeros_buf if track_branch else None,
        cache=cache_s,
    )
    final, (ys, brs, auxs) = jax.lax.scan(
        tick, init, (xs, masks, poss, jnp.arange(tk))
    )

    # microbatch m exits the last stage at tick m + S - 1. The exit streams
    # get the mirror treatment of the feed streams: pin the per-tick layout
    # before the slice+reshape back to [B, ...] so the drain (and its
    # autodiff transpose) reshards via cheap collectives instead of a full
    # rematerialization.
    ys = constrain(ys, None, ("data", "fsdp"))
    hidden = constrain(
        ys[S - 1 :].reshape((B,) + x.shape[1:]), ("data", "fsdp")
    )
    branch_input = None
    if track_branch:
        brs = constrain(brs, None, ("data", "fsdp"))
        branch_input = constrain(
            brs[S - 1 :].reshape((B,) + x.shape[1:]), ("data", "fsdp")
        )
    new_cache = None
    if cache is not None:
        new_cache = jax.tree_util.tree_map(
            lambda c, orig: c.reshape(orig.shape), final.cache, cache
        )
    # each valid (layer, microbatch) pair contributed its weighted statistics
    # exactly once; normalization happens in the caller (router_aux_summary)
    return hidden, branch_input, new_cache, jnp.sum(auxs, axis=0)
