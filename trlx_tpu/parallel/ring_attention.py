"""Ring attention: exact causal attention over a ``sequence``-sharded mesh axis.

Long-context / context parallelism is a first-class capability here, unlike the
reference, whose only sequence story is Megatron SP (activations gathered
before the heads, ``trlx/models/modeling_nemo_ilql.py:672-677``) with sequence
length capped by config (SURVEY.md §5 "Long-context"). Ring attention removes
the cap: each device holds one ``T/n`` chunk of Q/K/V, K/V chunks rotate around
the ring via ``lax.ppermute`` over ICI, and the online-softmax accumulator
combines per-chunk ``(out, lse)`` pairs — peak memory per device stays
O(T/n · d) while the math is bit-for-bit the full-sequence softmax (up to f32
rounding).

**Causal load balance — zigzag placement.** With contiguous chunks the causal
mask is a wall-clock disaster: device 0's queries see one chunk, device n−1's
see all n, and since ring steps are lockstep, every step costs as much as its
busiest device — the causal 2× FLOP saving evaporates. Zigzag placement fixes
this: the sequence is split into 2n half-chunks and device i holds halves
``i`` and ``2n−1−i``, so every device owns one early and one late span and
per-step work is near-uniform (see :func:`ring_schedule_work` for the
schedule model; the ring tests assert the balance). The permutation is a pair
of gathers around the attention call — O(T·H·D) bandwidth, negligible next to
the O(T²·D/n) attention at ring-scale sequence lengths.

**Forward**: n ring steps; per step, one flash-attention kernel call per
(local-half × visiting-half) pair with slot offsets selecting global
positions; fully-future pairs cost ~nothing (the kernel's k-block loop
collapses to zero iterations).

**Backward (custom VJP)**: one ring sweep carrying ``(k, v, mask, dk, dv)``;
each step runs the *fused* dq+dk+dv kernel
(``trlx_tpu/ops/flash_attention.py``) using the global logsumexp saved from
the forward — after n rotations every dk/dv accumulator is back on its home
device, complete. XLA overlaps each ppermute with the next step's kernels
since the Python loop is unrolled.

**ALiBi** is supported: global token positions (cumsum of the mask, computed
before sharding) ride the ring alongside K/V, and the kernel applies the
per-head slope from true positions — left-padded prompts included.
"""

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from trlx_tpu.ops.flash_attention import (
    NEG_INF,
    flash_attention,
    flash_attention_bwd_chunk,
)


def _combine(out_a, lse_a, out_b, lse_b):
    """Merge two normalized partial-softmax results via their logsumexps.

    out/lse shapes: [B, T, H, D] / [B, H, T]. Rows masked everywhere carry the
    ``NEG_INF`` sentinel and zero output on both sides, which this preserves.
    """
    m = jnp.maximum(lse_a, lse_b)
    w_a = jnp.where(lse_a > 0.5 * NEG_INF, jnp.exp(lse_a - m), 0.0)
    w_b = jnp.where(lse_b > 0.5 * NEG_INF, jnp.exp(lse_b - m), 0.0)
    denom = w_a + w_b
    safe = jnp.where(denom > 0.0, denom, 1.0)
    lse = jnp.where(denom > 0.0, m + jnp.log(safe), NEG_INF)
    wa = (w_a / safe).transpose(0, 2, 1)[..., None]  # [B, T, H, 1]
    wb = (w_b / safe).transpose(0, 2, 1)[..., None]
    out = out_a * wa + out_b * wb
    return out, lse


def zigzag_order(T: int, n: int) -> np.ndarray:
    """Global→zigzag gather indices: device i's shard holds half-chunks
    ``i`` and ``2n−1−i`` of the 2n-way split."""
    half = T // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * half, (i + 1) * half))
        order.extend(range((2 * n - 1 - i) * half, (2 * n - i) * half))
    return np.asarray(order, np.int32)


def ring_schedule_work(n: int, placement: str) -> Tuple[List[float], float, float]:
    """Analytic causal-work schedule: per-ring-step wall cost (max over
    devices, in units of one full chunk-pair attention), total wall, and
    total useful work. The imbalance the zigzag placement removes is
    ``total_wall / (total_work / n)`` → ~2 for contiguous, →1 for zigzag."""

    def segs(dev):
        if placement == "contiguous":
            return [(dev, 1.0)]  # (offset in chunk units, length in chunks)
        return [(dev * 0.5, 0.5), ((2 * n - 1 - dev) * 0.5, 0.5)]

    def pair_cost(qoff, qlen, koff, klen):
        # visible fraction of the (qlen × klen) tile under k_slot <= q_slot
        q_lo, q_hi = qoff, qoff + qlen
        k_lo, k_hi = koff, koff + klen
        if k_hi <= q_lo:
            return qlen * klen  # fully past: dense
        if k_lo >= q_hi:
            return 0.0  # fully future: skipped
        return 0.5 * qlen * klen  # diagonal: half-causal

    per_step, total_work = [], 0.0
    for s in range(n):
        costs = []
        for dev in range(n):
            src = (dev - s) % n
            c = sum(
                pair_cost(qo, ql, ko, kl)
                for qo, ql in segs(dev)
                for ko, kl in segs(src)
            )
            costs.append(c)
        per_step.append(max(costs))
        total_work += sum(costs)
    return per_step, sum(per_step), total_work


def _make_ring_fn(axis, n, causal, alibi, zigzag, sm_scale, block_q, block_k, interpret, window=None):
    """Build the per-shard ring function (a custom-VJP closure)."""

    def segments(dev, Tl):
        """Local (start, length, global_slot_offset) spans of this shard."""
        if not zigzag:
            return [(0, Tl, dev * Tl)]
        half = Tl // 2
        return [(0, half, dev * half), (half, half, (2 * n - 1 - dev) * half)]

    def rotate(perm, *arrays):
        return tuple(jax.lax.ppermute(a, axis, perm) for a in arrays)

    @jax.custom_vjp
    def ring(q, k, v, key_mask, qpos, kpos, slopes):
        out, _ = _ring_fwd_impl(q, k, v, key_mask, qpos, kpos, slopes)
        return out

    def _ring_fwd_impl(q, k, v, key_mask, qpos, kpos, slopes):
        idx = jax.lax.axis_index(axis)
        B, Tl, H, D = q.shape
        perm = [(j, (j + 1) % n) for j in range(n)]
        q_segs = segments(idx, Tl)

        outs = [jnp.zeros((B, ql, H, D), jnp.float32) for _, ql, _ in q_segs]
        lses = [jnp.full((B, H, ql), NEG_INF, jnp.float32) for _, ql, _ in q_segs]
        kc, vc, mc, kpc = k, v, key_mask, kpos
        for s in range(n):
            src = (idx - s) % n
            for qi, (qs, ql, qoff) in enumerate(q_segs):
                for ks, kl, koff in segments(src, Tl):
                    o_s, l_s = flash_attention(
                        q[:, qs : qs + ql],
                        kc[:, ks : ks + kl],
                        vc[:, ks : ks + kl],
                        mc[:, ks : ks + kl],
                        causal=causal,
                        sm_scale=sm_scale,
                        q_offset=qoff,
                        k_offset=koff,
                        q_positions=qpos[:, qs : qs + ql] if alibi else None,
                        k_positions=kpc[:, ks : ks + kl] if alibi else None,
                        alibi_slopes=slopes if alibi else None,
                        block_q=block_q,
                        block_k=block_k,
                        interpret=interpret,
                        return_lse=True,
                        window=window,
                    )
                    outs[qi], lses[qi] = _combine(
                        outs[qi], lses[qi], o_s.astype(jnp.float32), l_s
                    )
            if s != n - 1:
                kc, vc, mc = rotate(perm, kc, vc, mc)
                if alibi:
                    (kpc,) = rotate(perm, kpc)
        out = jnp.concatenate(outs, axis=1)
        lse = jnp.concatenate(lses, axis=2)
        return out.astype(q.dtype), lse

    def ring_fwd(q, k, v, key_mask, qpos, kpos, slopes):
        out, lse = _ring_fwd_impl(q, k, v, key_mask, qpos, kpos, slopes)
        return out, (q, k, v, key_mask, qpos, kpos, slopes, out, lse)

    def ring_bwd(res, do):
        q, k, v, key_mask, qpos, kpos, slopes, out, lse = res
        idx = jax.lax.axis_index(axis)
        B, Tl, H, D = q.shape
        perm = [(j, (j + 1) % n) for j in range(n)]
        q_segs = segments(idx, Tl)

        delta = jnp.sum(
            do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
        ).transpose(0, 2, 1)  # [B, H, Tl]

        dq = jnp.zeros_like(q, jnp.float32)
        kc, vc, mc, kpc = k, v, key_mask, kpos
        dkc = jnp.zeros_like(k, jnp.float32)
        dvc = jnp.zeros_like(v, jnp.float32)
        for s in range(n):
            src = (idx - s) % n
            for qs, ql, qoff in q_segs:
                for ks, kl, koff in segments(src, Tl):
                    dq_s, dk_s, dv_s = flash_attention_bwd_chunk(
                        q[:, qs : qs + ql],
                        kc[:, ks : ks + kl],
                        vc[:, ks : ks + kl],
                        mc[:, ks : ks + kl],
                        lse[:, :, qs : qs + ql],
                        delta[:, :, qs : qs + ql],
                        do[:, qs : qs + ql],
                        causal=causal,
                        sm_scale=sm_scale,
                        q_offset=qoff,
                        k_offset=koff,
                        q_positions=qpos[:, qs : qs + ql] if alibi else None,
                        k_positions=kpc[:, ks : ks + kl] if alibi else None,
                        alibi_slopes=slopes if alibi else None,
                        block_q=block_q,
                        block_k=block_k,
                        interpret=interpret,
                        window=window,
                    )
                    dq = dq.at[:, qs : qs + ql].add(dq_s.astype(jnp.float32))
                    dkc = dkc.at[:, ks : ks + kl].add(dk_s.astype(jnp.float32))
                    dvc = dvc.at[:, ks : ks + kl].add(dv_s.astype(jnp.float32))
            # rotate the kv chunk together with its gradient accumulator;
            # after the full sweep each accumulator is home and complete
            kc, vc, mc, dkc, dvc = rotate(perm, kc, vc, mc, dkc, dvc)
            if alibi:
                (kpc,) = rotate(perm, kpc)
        return (
            dq.astype(q.dtype),
            dkc.astype(k.dtype),
            dvc.astype(v.dtype),
            jnp.zeros_like(key_mask),
            jnp.zeros_like(qpos),
            jnp.zeros_like(kpos),
            jnp.zeros_like(slopes),
        )

    ring.defvjp(ring_fwd, ring_bwd)
    return ring


def ring_flash_attention(
    q: jax.Array,  # [B, T, H, D] global (sequence-sharded or shardable)
    k: jax.Array,  # [B, T, H, D]
    v: jax.Array,  # [B, T, H, D]
    key_mask: jax.Array,  # [B, T]
    mesh: Mesh,
    *,
    axis: str = "sequence",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    q_positions: Optional[jax.Array] = None,  # [B, T] (alibi)
    k_positions: Optional[jax.Array] = None,  # [B, T] (alibi)
    alibi_slopes: Optional[jax.Array] = None,  # [H]
    placement: str = "auto",  # auto | zigzag | contiguous
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,  # sliding-window width (slot distance)
) -> jax.Array:
    """Exact attention with K/V rotating over the ``axis`` mesh ring.

    T must be divisible by ``mesh.shape[axis]``. Falls back to a single flash
    call when the axis has size 1. Differentiable (custom ring VJP). Must be
    called under ``jit`` when the ring is active: partially-manual shard_map
    (``axis_names={axis}``) is unsupported in eager mode.

    ``placement="auto"`` uses zigzag half-chunk placement whenever it pays
    (causal, T divisible by 2n) and contiguous otherwise.
    """
    n = mesh.shape[axis]
    if n == 1:
        return flash_attention(
            q, k, v, key_mask,
            causal=causal, sm_scale=sm_scale,
            q_positions=q_positions, k_positions=k_positions,
            alibi_slopes=alibi_slopes,
            block_q=block_q, block_k=block_k, interpret=interpret,
            window=window,
        )
    B, T, H, D = q.shape
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by ring size {n}")
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    alibi = alibi_slopes is not None

    if placement == "auto":
        placement = "zigzag" if causal and T % (2 * n) == 0 else "contiguous"
    if placement == "zigzag" and T % (2 * n):
        raise ValueError(f"zigzag needs T divisible by 2n={2 * n}, got T={T}")
    zigzag = placement == "zigzag"

    if alibi:
        if q_positions is None or k_positions is None:
            raise ValueError("alibi ring attention needs q_positions/k_positions")
        qpos, kpos = q_positions.astype(jnp.int32), k_positions.astype(jnp.int32)
        slopes = alibi_slopes.astype(jnp.float32)
    else:
        qpos = jnp.zeros((B, T), jnp.int32)
        kpos = qpos
        slopes = jnp.zeros((H,), jnp.float32)

    if zigzag:
        order = jnp.asarray(zigzag_order(T, n))
        inverse = jnp.asarray(np.argsort(zigzag_order(T, n)))
        q, k, v = (jnp.take(x, order, axis=1) for x in (q, k, v))
        key_mask = jnp.take(key_mask, order, axis=1)
        qpos = jnp.take(qpos, order, axis=1)
        kpos = jnp.take(kpos, order, axis=1)

    ring = _make_ring_fn(
        axis, n, causal, alibi, zigzag, sm_scale, block_q, block_k, interpret,
        window,
    )
    shard = P(None, axis, None, None)
    in_specs = (shard, shard, shard, P(None, axis), P(None, axis), P(None, axis), P())
    if hasattr(jax, "shard_map"):
        f = jax.shard_map(
            ring,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=shard,
            axis_names={axis},
            check_vma=False,
        )
    else:
        # pre-0.5 jax: the public API lives in jax.experimental and spells
        # partial-manual mode as the complement (`auto` = the axes that
        # STAY automatic) instead of `axis_names`; `check_rep` is the old
        # name of `check_vma`
        from jax.experimental.shard_map import shard_map

        f = shard_map(
            ring,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=shard,
            check_rep=False,
            auto=frozenset(mesh.axis_names) - {axis},
        )
    out = f(q, k, v, key_mask, qpos, kpos, slopes)
    if zigzag:
        out = jnp.take(out, inverse, axis=1)
    return out
