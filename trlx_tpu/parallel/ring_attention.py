"""Ring attention: exact causal attention over a ``sequence``-sharded mesh axis.

Long-context / context parallelism is a first-class capability here, unlike the
reference, whose only sequence story is Megatron SP (activations gathered
before the heads, ``trlx/models/modeling_nemo_ilql.py:672-677``) with sequence
length capped by config (SURVEY.md §5 "Long-context"). Ring attention removes
the cap: each device holds one ``T/n`` chunk of Q/K/V, K/V chunks rotate around
the ring via ``lax.ppermute`` over ICI, and the online-softmax accumulator
combines per-chunk ``(out, lse)`` pairs — peak memory per device stays
O(T/n · d) while the math is bit-for-bit the full-sequence softmax (up to f32
rounding).

Forward: n ring steps, each a flash-attention kernel call
(``trlx_tpu/ops/flash_attention.py``) with slot offsets selecting the visiting
chunk's global position; causal chunk-skipping happens inside the kernel (its
k-block loop collapses to zero iterations for fully-future chunks).

Backward (custom VJP): one ring sweep carrying ``(k, v, mask, dk, dv)``; each
step computes this device's dq contribution and the visiting chunk's dk/dv
contribution using the *global* logsumexp saved from the forward — after n
rotations every dk/dv accumulator is back on its home device, complete. This
mirrors the published ring-attention backward; XLA overlaps the ppermute with
the kernels of the next step since the Python loop is unrolled.

Known trade-off (TODO): with causal masking the ring is load-imbalanced
(device 0's queries see 1 chunk, device n-1's see n) — zigzag/striped chunk
placement would fix this; dq and dk/dv currently recompute scores in two
kernels per step, a fused dq+dkv kernel would halve backward FLOPs.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from trlx_tpu.ops.flash_attention import (
    NEG_INF,
    flash_attention,
    flash_attention_bwd_chunk,
)


def _combine(out_a, lse_a, out_b, lse_b):
    """Merge two normalized partial-softmax results via their logsumexps.

    out/lse shapes: [B, T, H, D] / [B, H, T]. Rows masked everywhere carry the
    ``NEG_INF`` sentinel and zero output on both sides, which this preserves.
    """
    m = jnp.maximum(lse_a, lse_b)
    w_a = jnp.where(lse_a > 0.5 * NEG_INF, jnp.exp(lse_a - m), 0.0)
    w_b = jnp.where(lse_b > 0.5 * NEG_INF, jnp.exp(lse_b - m), 0.0)
    denom = w_a + w_b
    safe = jnp.where(denom > 0.0, denom, 1.0)
    lse = jnp.where(denom > 0.0, m + jnp.log(safe), NEG_INF)
    wa = (w_a / safe).transpose(0, 2, 1)[..., None]  # [B, T, H, 1]
    wb = (w_b / safe).transpose(0, 2, 1)[..., None]
    out = out_a * wa + out_b * wb
    return out, lse


def _make_ring_fn(axis, causal, sm_scale, block_q, block_k, interpret):
    """Build the per-shard ring function (a custom-VJP closure)."""

    @jax.custom_vjp
    def ring(q, k, v, key_mask):
        out, _ = _ring_fwd_impl(q, k, v, key_mask)
        return out

    def _ring_fwd_impl(q, k, v, key_mask):
        idx = jax.lax.axis_index(axis)
        n = jax.lax.axis_size(axis)
        B, Tl, H, D = q.shape
        q_off = idx * Tl
        perm = [(j, (j + 1) % n) for j in range(n)]

        out = jnp.zeros((B, Tl, H, D), jnp.float32)
        lse = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        kc, vc, mc = k, v, key_mask
        for s in range(n):
            src = (idx - s) % n
            o_s, l_s = flash_attention(
                q, kc, vc, mc,
                causal=causal, sm_scale=sm_scale,
                q_offset=q_off, k_offset=src * Tl,
                block_q=block_q, block_k=block_k,
                interpret=interpret, return_lse=True,
            )
            out, lse = _combine(out, lse, o_s.astype(jnp.float32), l_s)
            if s != n - 1:
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
                mc = jax.lax.ppermute(mc, axis, perm)
        return out.astype(q.dtype), lse

    def ring_fwd(q, k, v, key_mask):
        out, lse = _ring_fwd_impl(q, k, v, key_mask)
        return out, (q, k, v, key_mask, out, lse)

    def ring_bwd(res, do):
        q, k, v, key_mask, out, lse = res
        idx = jax.lax.axis_index(axis)
        n = jax.lax.axis_size(axis)
        B, Tl, H, D = q.shape
        q_off = idx * Tl
        perm = [(j, (j + 1) % n) for j in range(n)]

        delta = jnp.sum(
            do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
        ).transpose(0, 2, 1)  # [B, H, Tl]

        dq = jnp.zeros_like(q, jnp.float32)
        kc, vc, mc = k, v, key_mask
        dkc = jnp.zeros_like(k, jnp.float32)
        dvc = jnp.zeros_like(v, jnp.float32)
        for s in range(n):
            src = (idx - s) % n
            dq_s, dk_s, dv_s = flash_attention_bwd_chunk(
                q, kc, vc, mc, lse, delta, do,
                causal=causal, sm_scale=sm_scale,
                q_offset=q_off, k_offset=src * Tl,
                block_q=block_q, block_k=block_k, interpret=interpret,
            )
            dq = dq + dq_s.astype(jnp.float32)
            dkc = dkc + dk_s.astype(jnp.float32)
            dvc = dvc + dv_s.astype(jnp.float32)
            # rotate the kv chunk together with its gradient accumulator;
            # after the full sweep each accumulator is home and complete
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            mc = jax.lax.ppermute(mc, axis, perm)
            dkc = jax.lax.ppermute(dkc, axis, perm)
            dvc = jax.lax.ppermute(dvc, axis, perm)
        return (
            dq.astype(q.dtype),
            dkc.astype(k.dtype),
            dvc.astype(v.dtype),
            jnp.zeros_like(key_mask),
        )

    ring.defvjp(ring_fwd, ring_bwd)
    return ring


def ring_flash_attention(
    q: jax.Array,  # [B, T, H, D] global (sequence-sharded or shardable)
    k: jax.Array,  # [B, T, H, D]
    v: jax.Array,  # [B, T, H, D]
    key_mask: jax.Array,  # [B, T]
    mesh: Mesh,
    *,
    axis: str = "sequence",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Exact attention with K/V rotating over the ``axis`` mesh ring.

    T must be divisible by ``mesh.shape[axis]``. Falls back to a single flash
    call when the axis has size 1. Differentiable (custom ring VJP). Must be
    called under ``jit`` when the ring is active: partially-manual shard_map
    (``axis_names={axis}``) is unsupported in eager mode.
    """
    n = mesh.shape[axis]
    if n == 1:
        return flash_attention(
            q, k, v, key_mask,
            causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
    T = q.shape[1]
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by ring size {n}")
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)

    ring = _make_ring_fn(axis, causal, sm_scale, block_q, block_k, interpret)
    shard = P(None, axis, None, None)
    f = jax.shard_map(
        ring,
        mesh=mesh,
        in_specs=(shard, shard, shard, P(None, axis)),
        out_specs=shard,
        axis_names={axis},
        check_vma=False,
    )
    return f(q, k, v, key_mask)
