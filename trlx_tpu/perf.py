"""Hardware-free performance accounting for the hot programs.

The reference validates performance empirically on live GPUs
(``/root/reference/scripts/benchmark.sh:40-62``); on TPU, chip windows are
scarce, so regressions need a net that runs anywhere. This module builds a
trainer with **abstract weights** (``abstract_init=True`` — ShapeDtypeStruct
pytrees, nothing materialized, so even multi-B-param configs cost ~no memory),
lowers and compiles the three hot programs from SURVEY.md §3 —

1. ``generate``  — the jitted rollout decode loop (dominant cost in PPO),
2. ``score``     — the policy+frozen-reference scoring forward,
3. ``train_step``— the full donated/grad-accum optimization step,

— and reads XLA's compiled cost model (``cost_analysis()`` /
``memory_analysis()``). The numbers are backend-specific (budgets here are
CPU-backend numbers), but the *program* is the same one the trainer runs, so
program-level regressions — an extra forward sneaking in, a lost logits-span
restriction, a broken fusion, remat gone missing — show up as flop/byte
jumps regardless of backend. ``tests/test_perf_budget.py`` asserts these
against committed budgets (``benchmarks/perf_budgets.json``, regenerated via
``scripts/update_perf_budgets.py``).
"""

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from trlx_tpu.data.configs import TRLConfig

# Program shapes: small enough to compile fast on one CPU core, large enough
# that the per-token/per-layer structure (and its regressions) dominates.
DEFAULT_SHAPE = dict(batch_size=8, prompt_len=32, gen_len=16)

# The hot-program set per trainer — single source of truth for
# hot_program_costs' default, the budget generator, and the coverage test.
TRAINER_PROGRAMS = {
    "ppotrainer": ("generate", "score", "train_step"),
    "grpotrainer": ("generate", "score", "train_step"),
    "ilqltrainer": ("generate", "train_step"),
    "dpotrainer": ("train_step",),
    "sfttrainer": ("train_step",),
}

# Extra programs when train.continuous_batching is on: the refill prefill
# and the segment decode replace plain generate's monolithic loop as the
# rollout hot path (ops/slot_refill.py).
CONTINUOUS_BATCHING_PROGRAMS = ("cb_refill", "cb_segment")

# The same two hot programs over the paged KV backend (engine.backend:
# paged — gather → dense compute → scatter around a block pool,
# ops/paged_kv.py): budgeted separately so the gather/scatter overhead is
# itself under regression guard.
PAGED_ENGINE_PROGRAMS = ("paged_refill", "paged_decode")

# Paged backend with engine.decode_kernel: pallas — the segment decode is
# the in-place paged-attention kernel + fused sampling
# (ops/paged_attention.py); no per-segment gather/scatter exists in the
# program, and the budget pins that (a regression that reintroduces a
# pool-sized temporary shows up as a temp/byte jump). The refill prefill
# stays the gather-path program.
PAGED_KERNEL_PROGRAMS = ("paged_refill", "paged_decode_kernel")

# Paged backend with engine.speculative: the refill prefills BOTH caches
# (target through the block table + the dense draft cache) and the decode
# segment is the speculative round program — draft propose loop + the
# single multi-position verify forward + accept/commit
# (ops/speculative.py::spec_round_step inside ops/slot_refill.py). These
# two ARE the complete spec hot path: budgeting them pins "zero extra
# compiled programs per bucket beyond (spec refill, spec segment)".
PAGED_SPEC_PROGRAMS = ("paged_spec_refill", "paged_spec_segment")

# Speculative with the Pallas kernels (engine.decode_kernel /
# prefill_kernel: pallas): the spec refill commits the target prompt
# through the block table in place (ops/paged_prefill.py) and the spec
# segment's verify forward runs the multi-position paged kernel
# (ops/paged_attention.py::paged_verify_attention) — no per-round
# gather/scatter of the pool exists in either program, and the budget
# pair pins that the same way gpt2_test_paged_kernel does for plain
# decode.
PAGED_SPEC_KERNEL_PROGRAMS = (
    "paged_spec_prefill_kernel",
    "paged_spec_segment_kernel",
)


def _engine_programs(config: TRLConfig) -> Tuple[str, ...]:
    """The rollout programs ``train.continuous_batching`` adds, resolved
    from the engine config — the single selection point for
    ``_config_programs`` and ``hot_program_costs`` (a new engine program
    variant must be added exactly here). Paged program names compose from
    the two kernel knobs: the refill prefill is ``paged_refill`` (gather →
    dense prefill → scatter) or ``paged_prefill_kernel`` (the in-place
    Pallas prefill, ops/paged_prefill.py — no dense view in the program);
    ``engine.prefill_chunk`` adds the mid-chunk cache-only program
    ``paged_prefill_chunk``; the decode segment is ``paged_decode`` or
    ``paged_decode_kernel``."""
    if not bool(getattr(config.train, "continuous_batching", False)):
        return ()
    if int(getattr(config.engine, "speculative", 0)):
        # spec composes with both kernel knobs: the refill prefills the
        # target cache through the chosen prefill path (in place under
        # prefill_kernel: pallas), and the segment's verify forward runs
        # the multi-position paged kernel under decode_kernel: pallas
        # (ops/paged_attention.py::paged_verify_attention)
        refill = (
            "paged_spec_prefill_kernel"
            if config.engine.prefill_kernel == "pallas"
            else "paged_spec_refill"
        )
        progs = (refill,)
        if int(getattr(config.engine, "prefill_chunk", 0)):
            progs = progs + ("paged_prefill_chunk",)
        segment = (
            "paged_spec_segment_kernel"
            if config.engine.decode_kernel == "pallas"
            else "paged_spec_segment"
        )
        return progs + (segment,)
    if config.engine.backend == "paged":
        refill = (
            "paged_prefill_kernel"
            if config.engine.prefill_kernel == "pallas"
            else "paged_refill"
        )
        decode = (
            "paged_decode_kernel"
            if config.engine.decode_kernel == "pallas"
            else "paged_decode"
        )
        progs = (refill,)
        if int(getattr(config.engine, "prefill_chunk", 0)):
            progs = progs + ("paged_prefill_chunk",)
        return progs + (decode,)
    return CONTINUOUS_BATCHING_PROGRAMS


def _config_programs(config: TRLConfig) -> Tuple[str, ...]:
    return TRAINER_PROGRAMS[config.train.trainer.lower()] + _engine_programs(
        config
    )


def budget_programs() -> Dict[str, Tuple[str, ...]]:
    """Config name → the program set its budget must contain."""
    return {
        name: _config_programs(config)
        for name, (config, _) in budget_configs().items()
    }


def _build_abstract_trainer(config: TRLConfig):
    """Register all trainers and build the config's trainer on abstract
    (ShapeDtypeStruct) weights — the shared entry for every analysis path."""
    from trlx_tpu.trainer import get_trainer
    import trlx_tpu.trainer.dpo  # noqa: F401  (registration)
    import trlx_tpu.trainer.grpo  # noqa: F401
    import trlx_tpu.trainer.ilql  # noqa: F401
    import trlx_tpu.trainer.ppo  # noqa: F401
    import trlx_tpu.trainer.sft  # noqa: F401

    cls = get_trainer(config.train.trainer)
    return cls(config, reward_fn=lambda **kw: [0.0], abstract_init=True)


def _costs_of(lowered) -> Dict[str, float]:
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0]
    out = {
        "flops": float(ca.get("flops", -1.0)),
        "bytes_accessed": float(ca.get("bytes accessed", -1.0)),
    }
    try:
        mem = compiled.memory_analysis()
        out["temp_bytes"] = float(mem.temp_size_in_bytes)
        out["argument_bytes"] = float(mem.argument_size_in_bytes)
        out["output_bytes"] = float(mem.output_size_in_bytes)
    except Exception:  # memory_analysis is optional on some backends
        pass
    return out


def lowered_costs(lowered) -> Dict[str, float]:
    """Public seam for the observability layer: cost/memory analysis of an
    already-lowered program (``jit_fn.lower(...)``). The runtime MFU metric
    (``trlx_tpu/observability/metrics.py``) joins these flops against
    device-fenced step times, so the numerator is the *exact* compiled
    program the trainer runs — same accounting as :func:`hot_program_costs`."""
    return _costs_of(lowered)


def _train_batch_sds(trainer_name: str, B: int, P: int, N: int) -> Dict[str, Any]:
    """Abstract train-step batch for each supported trainer's loss contract."""
    SDS = jax.ShapeDtypeStruct
    T = P + N
    if trainer_name == "ppotrainer":
        return {
            "query_tensors": SDS((B, P), np.int32),
            "query_mask": SDS((B, P), np.int32),
            "response_tensors": SDS((B, N), np.int32),
            "response_mask": SDS((B, N), np.int32),
            "logprobs": SDS((B, N), np.float32),
            "values": SDS((B, N), np.float32),
            "rewards": SDS((B, N), np.float32),
        }
    if trainer_name == "sfttrainer":
        return {
            "input_ids": SDS((B, T), np.int32),
            "attention_mask": SDS((B, T), np.int32),
            "labels": SDS((B, T), np.int32),
        }
    if trainer_name == "grpotrainer":
        return {
            "query_tensors": SDS((B, P), np.int32),
            "query_mask": SDS((B, P), np.int32),
            "response_tensors": SDS((B, N), np.int32),
            "response_mask": SDS((B, N), np.int32),
            "logprobs": SDS((B, N), np.float32),
            "ref_logprobs": SDS((B, N), np.float32),
            "advantages": SDS((B,), np.float32),
        }
    if trainer_name == "dpotrainer":
        # interleaved (chosen, rejected) pair rows
        if B % 2:
            raise ValueError(f"DPO batches are (chosen, rejected) pairs: batch_size {B} must be even")
        return {
            "input_ids": SDS((B, T), np.int32),
            "attention_mask": SDS((B, T), np.int32),
            "out_mask": SDS((B, T), np.int32),
            "ref_logps": SDS((B,), np.float32),
        }
    if trainer_name == "ilqltrainer":
        A = N  # one action (response token) per generated position
        return {
            "input_ids": SDS((B, T), np.int32),
            "attention_mask": SDS((B, T), np.int32),
            "rewards": SDS((B, A), np.float32),
            "states_ixs": SDS((B, A + 1), np.int32),
            "actions_ixs": SDS((B, A), np.int32),
            "dones": SDS((B, A + 1), np.int32),
        }
    raise ValueError(f"no abstract batch builder for trainer '{trainer_name}'")


def hot_program_costs(
    config: TRLConfig,
    batch_size: int = DEFAULT_SHAPE["batch_size"],
    prompt_len: int = DEFAULT_SHAPE["prompt_len"],
    gen_len: int = DEFAULT_SHAPE["gen_len"],
    programs: Optional[Tuple[str, ...]] = None,
    trainer=None,
) -> Dict[str, Dict[str, float]]:
    """Compile the hot programs of a trainer for ``config`` with abstract
    weights and return their XLA cost/memory analysis, keyed by program.

    Supports PPO and GRPO (generate + score + train_step), ILQL (generate
    with the advantage-reshaping sampler hook + train_step), and DPO/SFT
    (train_step).
    Works for any causal-LM config the trainer accepts — including configs
    far too large to materialize on the analysis host (6B+ with
    ``scan_layers``): only shapes flow through tracing and compilation.

    When the config's mesh spans more than one device, the real GSPMD
    shardings are attached to every abstract input (params, optimizer
    moments, batch), so the compiled program is the true SPMD program —
    collectives included — and its per-device cost/memory is what gets
    budgeted. Requires the analysis host to expose that many (virtual)
    devices.
    """
    import contextlib
    import dataclasses

    from trlx_tpu.ops.sampling import GenerationConfig
    from trlx_tpu.parallel.mesh import set_global_mesh
    from trlx_tpu.parallel.sharding import batch_spec, param_shardings

    if trainer is None:
        trainer = _build_abstract_trainer(config)
    trainer_name = type(trainer).__name__.lower()
    if programs is None:
        programs = TRAINER_PROGRAMS.get(
            trainer_name, ("train_step",)
        ) + _engine_programs(config)

    B, P, N = batch_size, prompt_len, gen_len
    SDS = jax.ShapeDtypeStruct
    mesh = trainer.mesh
    multi = int(np.prod(list(mesh.shape.values()))) > 1

    def attach(tree, shardings):
        return jax.tree_util.tree_map(
            lambda s, sh: SDS(s.shape, s.dtype, sharding=sh), tree, shardings
        )

    def with_param_shardings(tree):
        if not multi:
            return tree
        return attach(tree, param_shardings(tree, mesh))

    def batch_sds(shape, dtype):
        if not multi:
            return SDS(shape, dtype)
        from jax.sharding import NamedSharding

        from trlx_tpu.parallel.sharding import fit_spec

        # analysis shapes need not divide the mesh (e.g. a small bench chunk
        # on a wide data axis): keep whatever prefix of the batch spec fits
        spec = fit_spec(mesh, shape, tuple(batch_spec(len(shape))))
        return SDS(shape, dtype, sharding=NamedSharding(mesh, spec))

    params = with_param_shardings(trainer.state.params)
    results: Dict[str, Dict[str, float]] = {}
    # sequence-parallel ops read the global mesh during tracing
    set_global_mesh(mesh)
    ctx = mesh if multi else contextlib.nullcontext()
    with ctx:
        if "generate" in programs:
            gen_kwargs = dict(trainer.generate_kwargs)
            gen_kwargs["max_new_tokens"] = N
            gen_config = GenerationConfig.from_gen_kwargs(
                gen_kwargs,
                eos_token_id=trainer.tokenizer.eos_token_id,
                pad_token_id=trainer.tokenizer.pad_token_id,
            )
            fn = trainer._get_generate_fn(gen_config, ())
            results["generate"] = _costs_of(
                fn.lower(
                    # under engine.speculative the serial sampler takes the
                    # (target, draft) tuple so abstract draft params lower
                    # as operands, not closures
                    trainer._engine_params(params),
                    batch_sds((B, P), np.int32),
                    batch_sds((B, P), np.int32),
                    jax.random.PRNGKey(0),
                )
            )

        cb_all = (
            CONTINUOUS_BATCHING_PROGRAMS
            + PAGED_ENGINE_PROGRAMS
            + PAGED_KERNEL_PROGRAMS
            + PAGED_SPEC_PROGRAMS
            + PAGED_SPEC_KERNEL_PROGRAMS
            + ("paged_prefill_kernel", "paged_prefill_chunk")
        )
        if any(p in programs for p in cb_all):
            # the continuous-batching rollout programs: the on-demand refill
            # prefill and the fixed-size segment decode (ops/slot_refill.py)
            # — lowered over an abstract SlotState so nothing materializes.
            # With engine.backend == "paged" the SAME entry points carry the
            # block-pool backend (gather/scatter around the dense compute),
            # budgeted under the paged_* names.
            gen_kwargs = dict(trainer.generate_kwargs)
            gen_kwargs["max_new_tokens"] = N
            gen_kwargs["per_row_rng"] = True
            gen_config = GenerationConfig.from_gen_kwargs(
                gen_kwargs,
                eos_token_id=trainer.tokenizer.eos_token_id,
                pad_token_id=trainer.tokenizer.pad_token_id,
            )
            seg = max(
                1,
                int(getattr(config.train, "continuous_batching_segment", 8) or 8),
            )
            fns = trainer._get_slot_refill_fns(gen_config, (), B, P, seg)
            state_sds = jax.eval_shape(fns.init_state)
            # spec programs take the (target, draft) params tuple — the
            # same value the engine holds (trainer._engine_params); plain
            # configs get `params` back unchanged
            eng_params = trainer._engine_params(params)
            refill_names = (
                "cb_refill", "paged_refill", "paged_prefill_kernel",
                "paged_spec_refill", "paged_spec_prefill_kernel",
            )
            if any(p in programs for p in refill_names):
                # the full-bucket (R = B) cold refill program: worst-case
                # refill cost; smaller buckets / prefix hits are cheaper
                refill_args = [
                    eng_params,
                    state_sds,
                    batch_sds((B, P), np.int32),
                    batch_sds((B, P), np.int32),
                    SDS((B,), np.int32),
                    SDS((B, 2), np.uint32),
                ]
                name = "cb_refill"
                if fns.paged is not None:
                    pk = getattr(fns, "prefill_kernel", "xla") == "pallas"
                    if getattr(fns, "speculative", 0):
                        name = (
                            "paged_spec_prefill_kernel"
                            if pk
                            else "paged_spec_refill"
                        )
                    elif pk:
                        name = "paged_prefill_kernel"
                    else:
                        name = "paged_refill"
                    TB = state_sds.cache.block_table.shape[1]
                    refill_args.append(SDS((B, TB), np.int32))
                results[name] = _costs_of(
                    fns.refill_program(B).lower(*refill_args)
                )
            if "paged_prefill_chunk" in programs:
                # one mid-chunk cache-only program at the configured chunk
                # size: span [0, chunk) over the full bucket — the program
                # the chunked-prefill scheduler dispatches between decode
                # segments (no logits, no SlotState row scatter)
                chunk = min(
                    max(int(config.engine.prefill_chunk), 1), max(P - 1, 1)
                )
                TB = state_sds.cache.block_table.shape[1]
                results["paged_prefill_chunk"] = _costs_of(
                    fns.prefill_chunk_program(B, 0, chunk).lower(
                        eng_params,
                        state_sds,
                        batch_sds((B, P), np.int32),
                        batch_sds((B, P), np.int32),
                        SDS((B, TB), np.int32),
                    )
                )
            if (
                "cb_segment" in programs
                or "paged_decode" in programs
                or "paged_decode_kernel" in programs
                or "paged_spec_segment" in programs
                or "paged_spec_segment_kernel" in programs
            ):
                if fns.paged is None:
                    name = "cb_segment"
                elif getattr(fns, "speculative", 0):
                    name = (
                        "paged_spec_segment_kernel"
                        if getattr(fns, "decode_kernel", "xla") == "pallas"
                        else "paged_spec_segment"
                    )
                elif getattr(fns, "decode_kernel", "xla") == "pallas":
                    name = "paged_decode_kernel"
                else:
                    name = "paged_decode"
                results[name] = _costs_of(
                    fns.decode_segment.lower(eng_params, state_sds)
                )

        if "score" in programs:
            fn = trainer._get_score_fn((B, P, N))
            results["score"] = _costs_of(
                fn.lower(
                    params,
                    with_param_shardings(trainer.ref_params),
                    batch_sds((B, P + N), np.int32),
                    batch_sds((B, P), np.int32),
                    batch_sds((B, N), np.int32),
                    batch_sds((B, N), np.int32),
                )
            )

        if "train_step" in programs:
            batch = _train_batch_sds(trainer_name, B, P, N)
            if multi:
                batch = {
                    k: batch_sds(v.shape, v.dtype) for k, v in batch.items()
                }
            state = trainer.state
            if multi:
                from trlx_tpu.trainer.base import _optimizer_state_shardings

                # derive moment shardings from the SHARDED params tree —
                # the helper reads each param leaf's .sharding, and the
                # abstract trainer's own params carry none
                opt_sh = _optimizer_state_shardings(
                    mesh, params, trainer.state.opt_state
                )
                opt = attach(trainer.state.opt_state, opt_sh)
                state = dataclasses.replace(state, params=params, opt_state=opt)
            fn = trainer._build_train_step()
            results["train_step"] = _costs_of(
                fn.lower(state, batch, SDS((), np.float32))
            )

    return results


def check_budget(
    costs: Dict[str, Dict[str, float]],
    budgets: Dict[str, Dict[str, float]],
    flop_tol: float = 0.05,
    byte_tol: float = 0.15,
    stale_frac: float = 0.5,
) -> Tuple[list, list]:
    """Compare measured program costs against committed budgets.

    Returns ``(violations, stale)``. A *violation* is a program whose flops
    exceed budget by > ``flop_tol`` (flops are deterministic — any growth is
    a program change) or whose bytes/temp memory exceed by > ``byte_tol``
    (byte accounting wobbles more across XLA minor versions). *Stale* flags
    programs now far **below** budget (> ``stale_frac`` improvement): not a
    failure of the code, but the budget no longer guards anything — rerun
    ``scripts/update_perf_budgets.py`` to ratchet it down.
    """
    tol = {"flops": flop_tol, "bytes_accessed": byte_tol, "temp_bytes": byte_tol}
    violations, stale = [], []
    for prog, budget in budgets.items():
        if prog not in costs:
            violations.append(f"{prog}: program missing from measurement")
            continue
        for metric, limit in budget.items():
            if metric not in tol or limit <= 0:
                continue
            got = costs[prog].get(metric)
            if got is None:
                continue
            if got > limit * (1.0 + tol[metric]):
                violations.append(
                    f"{prog}.{metric}: {got:.3e} exceeds budget {limit:.3e} "
                    f"(+{100 * (got / limit - 1):.1f}%, tol {100 * tol[metric]:.0f}%)"
                )
            elif got < limit * stale_frac:
                stale.append(
                    f"{prog}.{metric}: {got:.3e} is {100 * (1 - got / limit):.1f}% "
                    f"below budget {limit:.3e} — regenerate budgets to lock in the win"
                )
    return violations, stale


def budget_configs() -> Dict[str, Tuple[TRLConfig, Dict[str, int]]]:
    """The config matrix the perf net guards, name → (config, shape kwargs).

    Budgets are tied to an 8-virtual-device analysis host (the generator
    and the test conftest both force ``xla_force_host_platform_device_count
    =8``): configs with the default ``data=-1`` compile as dp8 SPMD
    programs, and the explicit-mesh entries compose fsdp/tp/sp.

    - ``gpt2_test``: tiny PPO — exercised in the fast test tier so the net
      runs in the <5-min loop;
    - ``gpt2_test_cb``: the same tiny PPO with ``train.continuous_batching``
      — adds the slot-refill rollout programs (refill prefill + segment
      decode) to the guarded set;
    - ``gpt2_small``: the flagship bench model (BASELINE.md);
    - ``gptj_6b_scan``: the large-model path — scan_layers + full remat, the
      program shape that runs on pods. Abstract weights: never materialized;
    - ``ilql_gpt2_test`` / ``sft_gpt2_test``: the other two reference
      algorithms' programs (ILQL: twin-Q/CQL train step + the
      advantage-reshaping sampler; SFT: masked-CE step);
    - ``grpo_gpt2_test`` / ``dpo_gpt2_test``: the beyond-reference
      algorithms (GRPO: head-less policy + hydra-ref scoring; DPO:
      paired-completion logp step);
    - ``ppo_t5_test``: the seq2seq leg — T5 encode/decode generate,
      teacher-forced scoring with the decoder hydra branch, seq2seq step.
    """
    from trlx_tpu.data.default_configs import (
        default_dpo_config,
        default_grpo_config,
        default_ilql_config,
        default_ppo_config,
        default_sft_config,
    )

    base = default_ppo_config()
    return {
        "gpt2_test": (
            base.evolve(
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_test_cb": (
            # the continuous-batching rollout programs (refill prefill +
            # segment decode) on the tiny config — guards the slot-refill
            # hot path the same way gpt2_test guards plain generate
            base.evolve(
                train=dict(continuous_batching=True),
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_test_paged": (
            # the paged-KV engine hot path (paged_refill + paged_decode):
            # gather/scatter around the dense compute over a block pool —
            # guards the new engine backend's per-program overhead
            # (docs/PERFORMANCE.md engine section)
            base.evolve(
                train=dict(continuous_batching=True),
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                engine=dict(backend="paged", kv_block_size=8, prefix_cache=True),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_test_paged_kernel": (
            # the paged engine with engine.decode_kernel: pallas — the
            # in-place paged-attention decode kernel + fused sampling
            # replace the per-segment gather/scatter (paged_refill +
            # paged_decode_kernel). The pair of budgets (this and
            # gpt2_test_paged) is the standing program-level record that
            # the kernel path carries no pool-sized temporaries.
            base.evolve(
                train=dict(continuous_batching=True),
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                engine=dict(
                    backend="paged", kv_block_size=8, prefix_cache=True,
                    decode_kernel="pallas",
                ),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_test_paged_prefill": (
            # the fully in-place paged engine with chunked-prefill
            # scheduling: paged_prefill_kernel (refill prefill through the
            # block table, no dense view — ops/paged_prefill.py),
            # paged_prefill_chunk (the mid-chunk cache-only span program
            # the scheduler interleaves with decode segments), and
            # paged_decode_kernel. Together with gpt2_test_paged this is
            # the standing program-level record that the prefill kernel
            # path carries no pool-sized gather/scatter temporaries.
            base.evolve(
                train=dict(continuous_batching=True),
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                engine=dict(
                    backend="paged", kv_block_size=8, prefix_cache=True,
                    decode_kernel="pallas", prefill_kernel="pallas",
                    prefill_chunk=8,
                ),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_test_spec": (
            # speculative continuous batching (engine.speculative): the
            # spec refill (target prefill through the block table + the
            # dense draft-cache prefill) and the speculative segment (the
            # draft-propose loop + single multi-position verify forward
            # per round, ops/speculative.py::spec_round_step). The pair of
            # budgets is the standing record that speculation adds exactly
            # these two programs per bucket — nothing else.
            base.evolve(
                train=dict(continuous_batching=True),
                model=dict(
                    model_path="builtin:gpt2-test", num_layers_unfrozen=1,
                    draft_model_path="builtin:gpt2-test", draft_gamma=4,
                ),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                engine=dict(
                    backend="paged", kv_block_size=8, prefix_cache=True,
                    speculative=4,
                ),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_test_spec_kernel": (
            # speculative over the Pallas kernels (decode_kernel +
            # prefill_kernel: pallas): the spec refill commits prompt K/V
            # through the block table in place and the spec segment's
            # verify forward is the multi-position paged kernel
            # (paged_spec_prefill_kernel + paged_spec_segment_kernel).
            # Paired with gpt2_test_spec, this is the standing
            # program-level record that composing speculation with the
            # in-place kernels deletes the per-round pool gather/scatter
            # without adding programs per bucket.
            base.evolve(
                train=dict(continuous_batching=True),
                model=dict(
                    model_path="builtin:gpt2-test", num_layers_unfrozen=1,
                    draft_model_path="builtin:gpt2-test", draft_gamma=4,
                ),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                engine=dict(
                    backend="paged", kv_block_size=8, prefix_cache=True,
                    speculative=4, decode_kernel="pallas",
                    prefill_kernel="pallas",
                ),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_test_loss_kernel": (
            # the fused learner-step kernel (method.loss_kernel: pallas):
            # train_step compiles with GAE + whitening + the clipped
            # losses as ONE fused program (ops/fused_loss.py) instead of
            # the staged chain. Paired with gpt2_test, this budget is the
            # standing record of the fused program's compiled cost — a
            # regression that splits the fusion back into staged [B, R]
            # HBM round-trips shows up as a bytes/temp jump here.
            base.evolve(
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                method=dict(loss_kernel="pallas"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "ilql_gpt2_test": (
            default_ilql_config().evolve(
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=-1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "sft_gpt2_test": (
            default_sft_config().evolve(
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=-1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "ppo_t5_test": (
            base.evolve(
                model=dict(
                    model_path="builtin:t5-test",
                    model_arch_type="seq2seq",
                    num_layers_unfrozen=1,
                ),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "grpo_gpt2_test": (
            default_grpo_config().evolve(
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "dpo_gpt2_test": (
            default_dpo_config().evolve(
                model=dict(model_path="builtin:gpt2-test", num_layers_unfrozen=-1),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gpt2_small": (
            base.evolve(
                model=dict(model_path="builtin:gpt2-small", num_layers_unfrozen=2),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "gptj_6b_scan": (
            base.evolve(
                model=dict(model_path="builtin:gptj-6b", num_layers_unfrozen=2),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                parallel=dict(scan_layers=True, remat="full"),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=8),
        ),
        "gptj_6b_fsdp2_tp2_sp2": (
            # the true SPMD program over an 8-device mesh: per-device
            # cost/memory incl. the collectives GSPMD inserts — guards the
            # sharded hot paths (a lost sharding shows up as an 8x jump)
            base.evolve(
                model=dict(model_path="builtin:gptj-6b", num_layers_unfrozen=2),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                parallel=dict(
                    data=1, fsdp=2, model=2, sequence=2,
                    scan_layers=True, remat="full",
                ),
            ),
            dict(batch_size=8, prompt_len=32, gen_len=16),
        ),
        "neox_20b_tp4_ilql": (
            # megatron_20b-shaped ILQL (matches the reference's
            # ``configs/nemo_configs/megatron_20b.yaml:53-57``: TP4,
            # seq 1024, hidden 6144, 44 layers) in its v4-16 capacity
            # recipe: TP4 × fsdp2, bf16 params, blockwise-int8 Adam —
            # 17.2 GiB/device state, see ``tests/test_capacity_20b.py``.
            # Guards the >20B-scale hot programs end to end (the rows the
            # round-4 verdict held "partial" for lack of at-scale evidence).
            default_ilql_config().evolve(
                train=dict(seq_length=1088, batch_size=4),
                model=dict(
                    model_path="builtin:gptneox-20b", num_layers_unfrozen=-1
                ),
                tokenizer=dict(tokenizer_path="builtin:bytes"),
                optimizer=dict(
                    name="adamw_8bit", kwargs=dict(lr=1e-5, weight_decay=1e-6)
                ),
                parallel=dict(
                    model=4, fsdp=2, scan_layers=True, remat="full",
                    param_dtype="bfloat16",
                ),
            ),
            dict(batch_size=4, prompt_len=1024, gen_len=16),
        ),
    }


def plan(
    config: TRLConfig,
    batch_size: int = DEFAULT_SHAPE["batch_size"],
    prompt_len: int = DEFAULT_SHAPE["prompt_len"],
    gen_len: int = DEFAULT_SHAPE["gen_len"],
    programs: Optional[Tuple[str, ...]] = None,
) -> Dict[str, Any]:
    """Capacity plan for a config without touching an accelerator: param /
    optimizer / gradient bytes per device (exact, from the abstract trees
    and their shardings) plus each hot program's compiled cost and temp
    memory. Answers "will this config fit?" before a pod is ever booked.

    ``temp_bytes`` comes from the CPU backend's compiled buffer assignment —
    indicative, not a TPU HBM guarantee; the weight/optimizer numbers are
    exact arithmetic.
    """
    from trlx_tpu.parallel.sharding import param_shardings

    trainer = _build_abstract_trainer(config)
    mesh = trainer.mesh
    n_dev = int(np.prod(list(mesh.shape.values())))

    params = trainer.state.params
    p_shard = param_shardings(params, mesh)

    def shard_factor(leaf, sh):
        # how many ways this leaf is actually split (replicated axes excluded)
        try:
            return int(np.prod(leaf.shape)) // int(
                np.prod(sh.shard_shape(leaf.shape))
            )
        except Exception:
            return 1

    def sharded_bytes(tree, shardings):
        return sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize // shard_factor(l, s)
            for l, s in zip(
                jax.tree_util.tree_leaves(tree),
                jax.tree_util.tree_leaves(shardings),
            )
        )

    n_params = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params))
    param_bytes_dev = sharded_bytes(params, p_shard)
    from trlx_tpu.trainer.base import _optimizer_state_shardings

    opt_sh = _optimizer_state_shardings(
        mesh,
        jax.tree_util.tree_map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            params,
            p_shard,
        ),
        trainer.state.opt_state,
    )
    opt_bytes_dev = sharded_bytes(trainer.state.opt_state, opt_sh)

    # programs=() skips compilation entirely — the weight/optimizer
    # arithmetic alone is near-instant even at 20B+
    costs = hot_program_costs(
        config,
        batch_size=batch_size,
        prompt_len=prompt_len,
        gen_len=gen_len,
        programs=programs,
        trainer=trainer,
    )
    return {
        "mesh": {k: v for k, v in mesh.shape.items() if v > 1} or {"single_device": 1},
        "n_devices": n_dev,
        "n_params": n_params,
        "per_device": {
            "param_bytes": param_bytes_dev,
            "optimizer_bytes": opt_bytes_dev,
            "grad_bytes_upper_bound": param_bytes_dev,
        },
        "programs": costs,
        "note": (
            "weights/optimizer: exact arithmetic over the sharded abstract "
            "trees; program temp_bytes: CPU-backend buffer assignment, "
            "indicative only"
        ),
    }


def main(argv=None) -> int:
    import argparse
    import json as _json

    parser = argparse.ArgumentParser(
        description="Capacity planner: compiled cost + memory plan for a "
        "config, no accelerator or weights needed (abstract lowering)."
    )
    parser.add_argument("config", help="TRLConfig YAML path")
    parser.add_argument("--batch-size", type=int, default=DEFAULT_SHAPE["batch_size"])
    parser.add_argument("--prompt-len", type=int, default=DEFAULT_SHAPE["prompt_len"])
    parser.add_argument("--gen-len", type=int, default=DEFAULT_SHAPE["gen_len"])
    args = parser.parse_args(argv)

    # size the virtual device pool to the config's explicit mesh axes
    # BEFORE any jax backend initializes — a laptop has one device, and a
    # sharded plan needs mesh-product many
    import os

    import yaml

    with open(args.config) as f:
        raw = yaml.safe_load(f) or {}
    par = raw.get("parallel") or {}
    needed = 1
    has_auto_axis = False
    for axis in ("data", "pipe", "fsdp", "model", "sequence", "expert"):
        v = int(par.get(axis, 1))
        if v > 1:
            needed *= v
        elif v == -1:
            has_auto_axis = True
    # a -1 axis absorbs whatever devices exist, so the plan depends on the
    # virtual pool size; default it to (at least) 8 — the mesh the committed
    # budgets (benchmarks/perf_budgets.json) and the test conftest use — so
    # CLI output is comparable to them on any machine. The pool must stay a
    # multiple of the fixed-axes product or mesh construction rejects it.
    if has_auto_axis and needed < 8:
        needed = needed * -(-8 // needed)
    if needed > 1 and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={needed}"
        ).strip()

    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()
    config = TRLConfig.load_yaml(args.config)
    result = plan(
        config,
        batch_size=args.batch_size,
        prompt_len=args.prompt_len,
        gen_len=args.gen_len,
    )
    gib = 2**30
    pd = result["per_device"]
    print(_json.dumps(result, indent=2))
    print(
        f"\n# {result['n_params'] / 1e9:.2f}B params on {result['n_devices']} "
        f"device(s) {result['mesh']}: "
        f"{pd['param_bytes'] / gib:.2f} GiB weights + "
        f"{pd['optimizer_bytes'] / gib:.2f} GiB optimizer + "
        f"<= {pd['grad_bytes_upper_bound'] / gib:.2f} GiB grads per device "
        f"(+ program temps, see programs.*.temp_bytes)",
        flush=True,
    )
    if has_auto_axis:
        print(
            f"# per-device numbers are for THIS {result['n_devices']}-device "
            "mesh; -1 axes resize with the pool (committed budgets use 8)",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
