"""Benchmark suite + A-vs-B comparator.

Capability parity with the reference's empirical regression mechanism —
``scripts/benchmark.sh:1-62`` (fixed task list at fixed seeds, metrics
logged per step) plus ``trlx/reference.py:1-103`` (branch-vs-main report) —
rebuilt for offline TPU use: every task's stats stream to a JSONL file via
the built-in jsonl tracker, and the comparator renders a markdown report of
final/mean metric deltas between two runs instead of a W&B report.

Usage::

    python scripts/benchmark.py run --output-dir benchmarks/main --scale ci
    python scripts/benchmark.py run --output-dir benchmarks/branch --scale ci
    python scripts/benchmark.py report benchmarks/main benchmarks/branch

Suite (same shape as ``benchmark.sh:40-62``): randomwalks PPO + ILQL (the
CPU-scale anchors) and the sentiment quartet (PPO / ILQL / SFT / PPO-T5).
``--scale ci`` shrinks every task to smoke size; ``--scale full`` runs the
example defaults.
"""

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from trlx_tpu.utils import get_git_tag, logging

logger = logging.get_logger(__name__)

_EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")

# Fixed seeds: runs are comparable across branches (benchmark.sh pins its
# tasks the same way via the examples' default configs).
_SEED = 1000


def provenance() -> Dict[str, Any]:
    """Backend/toolchain provenance block stamped into every A/B artifact.

    The bench entry points run on whatever backend JAX selected and used
    to record only a bare ``backend`` string — an artifact produced by a
    silent CPU fallback was indistinguishable from a chip run at a glance
    (ROADMAP: "all perf evidence is CPU-scale with no way to tell from the
    artifact"). Every measure_* function now embeds this block, and
    ``scripts/stamp_benchmark_provenance.py`` retrofits committed
    artifacts.
    """
    import platform

    import jax

    dev = jax.devices()[0]
    return {
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "num_devices": jax.device_count(),
        "jax_version": jax.__version__,
        "python_version": platform.python_version(),
        # UTC ISO-8601 Z — the repo's artifact timestamp convention
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

# task name → (script path, CI-scale hparam overrides)
TASKS: Dict[str, Tuple[str, Dict[str, Any]]] = {
    "ppo_randomwalks": (
        os.path.join(_EXAMPLES, "randomwalks", "ppo_randomwalks.py"),
        {
            "train.total_steps": 4, "train.batch_size": 8, "train.eval_interval": 2,
            "method.num_rollouts": 8, "method.chunk_size": 8, "method.ppo_epochs": 1,
        },
    ),
    "ilql_randomwalks": (
        os.path.join(_EXAMPLES, "randomwalks", "ilql_randomwalks.py"),
        {"train.total_steps": 4, "train.batch_size": 8, "train.eval_interval": 2},
    ),
    "ppo_sentiments": (
        os.path.join(_EXAMPLES, "ppo_sentiments.py"),
        {
            "train.total_steps": 2, "train.batch_size": 4, "train.eval_interval": 2,
            "train.seq_length": 32, "method.num_rollouts": 4, "method.chunk_size": 4,
            "method.ppo_epochs": 1, "method.gen_kwargs.max_new_tokens": 8,
            "model.model_path": "builtin:gpt2-test", "tokenizer.tokenizer_path": "builtin:bytes",
        },
    ),
    "ilql_sentiments": (
        os.path.join(_EXAMPLES, "ilql_sentiments.py"),
        {
            "train.total_steps": 2, "train.batch_size": 4, "train.eval_interval": 2,
            "train.seq_length": 32,
            "model.model_path": "builtin:gpt2-test", "tokenizer.tokenizer_path": "builtin:bytes",
        },
    ),
    "sft_sentiments": (
        os.path.join(_EXAMPLES, "sft_sentiments.py"),
        {
            "train.total_steps": 2, "train.batch_size": 4, "train.eval_interval": 2,
            "train.seq_length": 32,
            "model.model_path": "builtin:gpt2-test", "tokenizer.tokenizer_path": "builtin:bytes",
        },
    ),
    "ppo_sentiments_t5": (
        os.path.join(_EXAMPLES, "ppo_sentiments_t5.py"),
        {
            "train.total_steps": 2, "train.batch_size": 4, "train.eval_interval": 2,
            "train.seq_length": 32, "method.num_rollouts": 4, "method.chunk_size": 4,
            "method.ppo_epochs": 1, "method.gen_kwargs.max_new_tokens": 8,
            "model.model_path": "builtin:t5-test", "tokenizer.tokenizer_path": "builtin:bytes",
        },
    ),
    "grpo_sentiments": (
        os.path.join(_EXAMPLES, "grpo_sentiments.py"),
        {
            "train.total_steps": 2, "train.batch_size": 8, "train.eval_interval": 2,
            "train.seq_length": 56, "method.num_rollouts": 8, "method.chunk_size": 8,
            "method.group_size": 4, "method.ppo_epochs": 1,
            "model.model_path": "builtin:gpt2-test", "tokenizer.tokenizer_path": "builtin:bytes",
        },
    ),
    "dpo_sentiments": (
        os.path.join(_EXAMPLES, "dpo_sentiments.py"),
        {
            "train.total_steps": 2, "train.batch_size": 4, "train.eval_interval": 2,
            "train.seq_length": 48, "method.gen_kwargs.max_new_tokens": 8,
            "model.model_path": "builtin:gpt2-test", "tokenizer.tokenizer_path": "builtin:bytes",
        },
    ),
    "grpo_moe_mixtral": (
        os.path.join(_EXAMPLES, "grpo_moe_mixtral.py"),
        {
            "train.total_steps": 2, "train.batch_size": 8, "train.eval_interval": 2,
            "train.seq_length": 56, "method.num_rollouts": 8, "method.chunk_size": 8,
            "method.group_size": 4, "method.ppo_epochs": 1,
            "method.gen_kwargs.max_new_tokens": 8,
        },
    ),
    "ppo_speculative": (
        os.path.join(_EXAMPLES, "ppo_speculative.py"),
        {
            "train.total_steps": 2, "train.batch_size": 8, "train.eval_interval": 2,
            "train.seq_length": 48, "method.num_rollouts": 8, "method.chunk_size": 8,
            "method.ppo_epochs": 1, "method.gen_kwargs.max_new_tokens": 8,
            "model.model_path": "builtin:gpt2-test", "tokenizer.tokenizer_path": "builtin:bytes",
        },
    ),
}


def run_task(
    name: str,
    output_dir: str,
    scale: str = "ci",
    extra_env: Optional[Dict[str, str]] = None,
    timeout: Optional[float] = None,
) -> Dict[str, Any]:
    """Run one suite task as a subprocess; stats land in
    ``<output_dir>/<name>/stats.jsonl``; returns the task record."""
    script, ci_overrides = TASKS[name]
    task_dir = os.path.join(output_dir, name)
    os.makedirs(task_dir, exist_ok=True)
    hparams: Dict[str, Any] = {
        "train.seed": _SEED,
        "train.tracker": "jsonl",
        "train.logging_dir": task_dir,
        "train.checkpoint_dir": os.path.join(task_dir, "ckpts"),
        "train.checkpoint_interval": 10_000_000,
        "train.save_best": False,
    }
    if scale == "ci":
        hparams.update(ci_overrides)

    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(p for p in (pkg_root, env.get("PYTHONPATH")) if p)
    if extra_env:
        env.update(extra_env)

    t0 = time.time()
    with open(os.path.join(task_dir, "run.log"), "w") as log:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(script), json.dumps(hparams)],
            cwd=os.path.dirname(os.path.abspath(script)),
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
            timeout=timeout,
        )
    record = {
        "task": name,
        "rc": proc.returncode,
        "runtime_s": round(time.time() - t0, 1),
        "stats_path": os.path.join(task_dir, "stats.jsonl"),
    }
    throughput = _throughput_summary(record["stats_path"])
    if throughput:
        record["throughput"] = throughput
    logger.info(f"benchmark {name}: rc={proc.returncode} ({record['runtime_s']}s)")
    return record


_THROUGHPUT_KEYS = (
    "throughput/tokens_per_sec",
    "throughput/samples_per_sec",
    "throughput/mfu",
    "throughput/rollout_overlap_frac",
    "throughput/rollout_tokens_per_sec",
    "throughput/slot_utilization",
    "rollout/padded_decode_frac",
    "time/train_step",
    "time/rollout",
    "time/rollout_host",
)


def _throughput_summary(stats_path: str) -> Dict[str, float]:
    """Mean of the observability layer's per-step throughput fields over a
    task's stats stream — rides the suite's ``meta.json`` record so an A/B
    comparison carries speed context, not just metric curves."""
    if not os.path.exists(stats_path):
        return {}
    series: Dict[str, List[float]] = {}
    with open(stats_path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue
            for key in _THROUGHPUT_KEYS:
                value = record.get(key)
                if isinstance(value, (int, float)):
                    series.setdefault(key, []).append(float(value))
    return {k: round(sum(v) / len(v), 6) for k, v in series.items()}


def run_suite(
    output_dir: str,
    tasks: Optional[List[str]] = None,
    scale: str = "ci",
    extra_env: Optional[Dict[str, str]] = None,
    timeout: Optional[float] = None,
) -> List[Dict[str, Any]]:
    os.makedirs(output_dir, exist_ok=True)
    branch, commit = get_git_tag()
    meta = {"branch": branch, "commit": commit, "scale": scale, "time": time.strftime("%F %T")}
    records = [
        run_task(name, output_dir, scale, extra_env, timeout)
        for name in (tasks or list(TASKS))
    ]
    meta["tasks"] = records
    with open(os.path.join(output_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    return records


def _load_stats(run_dir: str, task: str) -> List[Dict[str, Any]]:
    path = os.path.join(run_dir, task, "stats.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


_KEY_METRICS = (
    "reward/mean", "metrics/optimality", "metrics/sentiments",
    "losses/total_loss", "losses/loss",
    "throughput/tokens_per_sec", "throughput/mfu",
    "throughput/rollout_overlap_frac",
    "throughput/rollout_tokens_per_sec",
    "throughput/slot_utilization",
    "rollout/padded_decode_frac",
)


def compare_runs(run_a: str, run_b: str, metrics: Optional[List[str]] = None) -> str:
    """Markdown A-vs-B report over the shared tasks of two suite runs
    (the ``trlx/reference.py:29-96`` metric-curves report, offline)."""

    def meta(run):
        path = os.path.join(run, "meta.json")
        return json.load(open(path)) if os.path.exists(path) else {}

    meta_a, meta_b = meta(run_a), meta(run_b)
    lines = [
        f"# Benchmark comparison",
        "",
        f"- A: `{run_a}` ({meta_a.get('branch')}@{meta_a.get('commit')})",
        f"- B: `{run_b}` ({meta_b.get('branch')}@{meta_b.get('commit')})",
        "",
        "| task | metric | A final | B final | Δ | A mean | B mean |",
        "|---|---|---|---|---|---|---|",
    ]
    tasks = sorted(
        {t for t in os.listdir(run_a) if os.path.isdir(os.path.join(run_a, t))}
        & {t for t in os.listdir(run_b) if os.path.isdir(os.path.join(run_b, t))}
    )
    for task in tasks:
        stats_a, stats_b = _load_stats(run_a, task), _load_stats(run_b, task)
        keys = metrics or [
            k for k in _KEY_METRICS
            if any(k in r for r in stats_a) and any(k in r for r in stats_b)
        ]
        for key in keys:
            series_a = [r[key] for r in stats_a if key in r]
            series_b = [r[key] for r in stats_b if key in r]
            if not series_a or not series_b:
                continue
            fa, fb = series_a[-1], series_b[-1]
            ma = sum(series_a) / len(series_a)
            mb = sum(series_b) / len(series_b)
            lines.append(
                f"| {task} | {key} | {fa:.4g} | {fb:.4g} | {fb - fa:+.4g} | {ma:.4g} | {mb:.4g} |"
            )
    return "\n".join(lines) + "\n"


def measure_speculative(
    policy_layers: int = 24,
    policy_hidden: int = 256,
    gamma: int = 4,
    batch_size: int = 8,
    prompt_len: int = 16,
    max_new_tokens: int = 32,
    rounds: int = 8,
    seed: int = _SEED,
) -> Dict[str, Any]:
    """Rollout-throughput A/B: plain sampler vs draft-and-verify speculative
    decoding (round-3 verdict weak#5 — acceptance was property-tested exact,
    but no artifact showed a wall-clock number).

    Policy: a ``policy_layers`` × ``policy_hidden`` gpt2 family model;
    draft: the stock 2-layer/64-hidden gpt2-test (same byte vocab). Both
    trainers come up through the public registry and generation runs through
    the trainer's jitted rollout path — the same program PPO's
    make_experience uses. Runs on whatever backend JAX selected, so the same
    entry produces CPU program-level ratios or on-chip numbers.

    Two caveats worth reading off the artifact rather than assuming:
    speculation wins only when the policy forward dominates (at gpt2-test
    scale the bookkeeping costs more than it saves — the committed artifact
    includes that sub-1.0 point deliberately), and the acceptance rate here
    reflects two *untrained* models' agreement — with a real distilled
    draft it is typically far higher, so the reported speedup is a floor
    for the harness, not a ceiling for the method.
    """
    import numpy as np

    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()  # honors TRLX_TPU_PLATFORM before any backend init

    import trlx_tpu.trainer.ppo  # noqa: F401  (registers PPOTrainer)
    from trlx_tpu.data.default_configs import default_ppo_config
    from trlx_tpu.trainer import get_trainer

    policy_extra = dict(
        num_layers=policy_layers,
        hidden_size=policy_hidden,
        num_heads=max(4, policy_hidden // 32),
        intermediate_size=4 * policy_hidden,
    )
    results: Dict[str, Any] = {
        "config": dict(
            policy=policy_extra,
            draft=dict(num_layers=2, hidden_size=64),
            gamma=gamma,
            batch_size=batch_size,
            prompt_len=prompt_len,
            max_new_tokens=max_new_tokens,
            rounds=rounds,
        )
    }
    for mode in ("plain", "speculative"):
        model_kwargs: Dict[str, Any] = dict(
            model_path="builtin:gpt2-test",
            num_layers_unfrozen=1,
            model_extra_kwargs=dict(policy_extra),
        )
        if mode == "speculative":
            model_kwargs.update(
                draft_model_path="builtin:gpt2-test", draft_gamma=gamma
            )
        cfg = default_ppo_config().evolve(
            train=dict(
                seq_length=prompt_len + max_new_tokens,
                batch_size=batch_size,
                total_steps=1,
                checkpoint_interval=10_000_000,
                tracker=None,
                seed=seed,
            ),
            model=model_kwargs,
            tokenizer=dict(tokenizer_path="builtin:bytes"),
            method=dict(
                num_rollouts=batch_size,
                chunk_size=batch_size,
                gen_kwargs=dict(
                    max_new_tokens=max_new_tokens, top_k=0, top_p=1.0, do_sample=True
                ),
            ),
        )
        trainer = get_trainer(cfg.train.trainer)(
            cfg, reward_fn=lambda **kw: [0.0] * batch_size
        )
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, 256, (batch_size, prompt_len)).astype(np.int32)
        mask = np.ones_like(ids)
        out = trainer.generate(ids, mask)  # compile warmup, excluded from timing
        import jax

        jax.block_until_ready(out.sequences)
        t0 = time.time()
        for _ in range(rounds):
            out = trainer.generate(ids, mask)
        jax.block_until_ready(out.sequences)
        dt = time.time() - t0
        results[mode] = {
            "samples_per_s": round(batch_size * rounds / dt, 3),
            "tokens_per_s": round(batch_size * rounds * max_new_tokens / dt, 1),
            "seconds": round(dt, 3),
        }
        if mode == "speculative":
            results[mode].update(
                {k.split("/")[-1]: v for k, v in trainer.last_spec_stats.items()}
            )
    results["speedup"] = round(
        results["speculative"]["samples_per_s"] / results["plain"]["samples_per_s"], 3
    )
    import jax

    results["backend"] = jax.default_backend()
    results["provenance"] = provenance()
    return results


def measure_continuous_batching(
    policy_layers: int = 8,
    policy_hidden: int = 128,
    batch_size: int = 16,
    prompt_len: int = 16,
    max_new_tokens: int = 96,
    num_rollouts: int = 64,
    absorb_frac: float = 0.08,
    segment_len: int = 8,
    rounds: int = 3,
    seed: int = _SEED,
) -> Dict[str, Any]:
    """Rollout-collection A/B: serial chunked decode vs continuous batching
    (slot-refill segment decode, docs/PERFORMANCE.md) on a synthetic
    heterogeneous-response-length workload.

    Length heterogeneity is synthesized with a transition ``logit_mask``
    whose first ``absorb_frac`` of the byte vocabulary allows only eos as
    the next token: each decode step absorbs with roughly that probability,
    so response lengths are ~geometric in ``[1, max_new_tokens]`` — the
    regime where the serial path's batch-tail padding waste is largest. Both
    modes sample with per-row RNG (``gen_kwargs.per_row_rng``), so they
    decode the *same* per-prompt sequences: the tokens-per-second ratio is a
    pure scheduling comparison, not a workload change
    (tests/test_continuous_batching.py pins the store equivalence).

    Reports per mode: ``throughput/rollout_tokens_per_sec``, per-chunk
    ``time/rollout``, ``rollout/padded_decode_frac`` and
    ``throughput/slot_utilization``, plus the wall-clock speedup. Runs on
    whatever backend JAX selected (CPU program-level ratios or on-chip
    numbers — the evidence chain runs it in ``scripts/tpu_evidence.py``).
    """
    import numpy as np

    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()  # honors TRLX_TPU_PLATFORM before any backend init

    import trlx_tpu.pipeline.offline_pipeline  # noqa: F401  (registration)
    import trlx_tpu.trainer.ppo  # noqa: F401  (registers PPOTrainer)
    from trlx_tpu.data.default_configs import default_ppo_config
    from trlx_tpu.pipeline import get_pipeline
    from trlx_tpu.trainer import get_trainer

    absorb_n = max(1, int(absorb_frac * 256))
    # builtin:bytes vocab: ids 0..255 bytes, 256 bos, 257 eos, 258 pad (=259)
    vocab, eos = 259, 257
    logit_mask = np.ones((vocab, vocab), bool)
    logit_mask[:absorb_n, :] = False
    logit_mask[:absorb_n, eos] = True

    policy_extra = dict(
        num_layers=policy_layers,
        hidden_size=policy_hidden,
        num_heads=max(4, policy_hidden // 32),
        intermediate_size=4 * policy_hidden,
    )
    results: Dict[str, Any] = {
        "config": dict(
            policy=policy_extra,
            batch_size=batch_size,
            prompt_len=prompt_len,
            max_new_tokens=max_new_tokens,
            num_rollouts=num_rollouts,
            absorb_frac=absorb_frac,
            segment_len=segment_len,
            rounds=rounds,
        )
    }

    def reward_fn(samples, prompts, outputs, **kwargs):
        return [float(sum(c in "aeiou" for c in o)) for o in outputs]

    rs = np.random.RandomState(seed)
    prompts = [
        "".join(chr(97 + c) for c in rs.randint(0, 26, prompt_len))
        for _ in range(max(num_rollouts, 4 * batch_size))
    ]

    for mode in ("serial", "continuous"):
        cfg = default_ppo_config().evolve(
            train=dict(
                seq_length=prompt_len + max_new_tokens,
                batch_size=batch_size,
                total_steps=1,
                checkpoint_interval=10_000_000,
                tracker=None,
                seed=seed,
                continuous_batching=(mode == "continuous"),
                continuous_batching_segment=segment_len,
            ),
            model=dict(
                model_path="builtin:gpt2-test",
                num_layers_unfrozen=1,
                model_extra_kwargs=dict(policy_extra),
            ),
            tokenizer=dict(tokenizer_path="builtin:bytes"),
            method=dict(
                num_rollouts=num_rollouts,
                chunk_size=batch_size,
                gen_kwargs=dict(
                    max_new_tokens=max_new_tokens, top_k=0, top_p=1.0,
                    do_sample=True, per_row_rng=True,
                ),
            ),
        )
        trainer = get_trainer(cfg.train.trainer)(
            cfg, reward_fn=reward_fn, logit_mask=logit_mask
        )
        trainer.add_prompt_pipeline(
            get_pipeline(cfg.train.pipeline)(prompts, prompt_len, trainer.tokenizer)
        )
        trainer.make_experience(num_rollouts)  # compile warmup, untimed
        t0 = time.time()
        for _ in range(rounds):
            trainer.store.clear_history()
            trainer.make_experience(num_rollouts)
        dt = time.time() - t0
        es = trainer.make_experience_stats
        lengths = [
            int(np.asarray(e.response_tensor).shape[0])
            for e in trainer.store.history
        ]
        results[mode] = {
            "seconds": round(dt, 3),
            "rollout_tokens_per_sec": round(
                float(es.get("throughput/rollout_tokens_per_sec", 0.0)), 1
            ),
            "time_rollout_s": round(float(es.get("time/rollout", 0.0)), 4),
            "padded_decode_frac": round(
                float(es.get("rollout/padded_decode_frac", 0.0)), 4
            ),
            "slot_utilization": round(
                float(es.get("throughput/slot_utilization", 0.0)), 4
            ),
            "response_len_mean": round(float(np.mean(lengths)), 2) if lengths else 0.0,
            "response_len_max": int(np.max(lengths)) if lengths else 0,
        }
        if mode == "continuous":
            results[mode]["refill_prefills"] = int(
                es.get("rollout/refill_prefills", 0)
            )
            results[mode]["segments"] = int(es.get("rollout/segments", 0))
    results["speedup"] = round(
        results["serial"]["seconds"] / max(results["continuous"]["seconds"], 1e-9), 3
    )
    results["padded_frac_drop"] = round(
        results["serial"]["padded_decode_frac"]
        - results["continuous"]["padded_decode_frac"],
        4,
    )
    import jax

    results["backend"] = jax.default_backend()
    results["provenance"] = provenance()
    return results


def measure_engine_paged(
    policy_layers: int = 8,
    policy_hidden: int = 128,
    batch_size: int = 16,
    prompt_len: int = 32,
    max_new_tokens: int = 96,
    group_size: int = 8,
    n_groups: int = 8,
    passes: int = 2,
    absorb_frac: float = 0.08,
    kv_block_size: int = 8,
    segment_len: int = 8,
    seed: int = _SEED,
) -> Dict[str, Any]:
    """Engine A/B: dense per-slot KV vs paged block-pool KV + prefix cache
    (docs/PERFORMANCE.md engine section) on a shared-prefix workload —
    ``n_groups`` distinct prompts × ``group_size`` identical members (the
    GRPO-group shape) driven through the engine for ``passes`` waves with
    FIXED params (the repeated-eval shape; a trained-params wave would
    flush the prefix cache, see ``ContinuousEngine.begin_collection``).

    Responses are ~geometric in ``[1, max_new_tokens]`` via an absorbing
    transition mask, so live tokens sit far below ``slots × max_length`` —
    the regime the paged pool exists for. Both modes decode the SAME
    per-row RNG streams and the harvest is asserted bit-identical inside
    this function, so every delta is bookkeeping, never a workload change.

    The two acceptance numbers (committed: benchmarks/ENGINE_PAGED_cpu.json):

    - ``kv_bytes_high_water`` (paged) vs ``kv_cache_bytes`` (dense): the
      paged pool's high-water is blocks-in-use × block bytes — live
      tokens — while the dense cache is ``B × (P + N)`` regardless;
    - ``prefill_tokens``: prefix-cache hits prefill only unshared
      suffixes, so the paged engine prefills strictly fewer prompt tokens
      (``prefix_tokens_saved`` = the columns skipped).
    """
    import numpy as np

    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()  # honors TRLX_TPU_PLATFORM before any backend init

    import jax

    from trlx_tpu.data.configs import ModelConfig
    from trlx_tpu.engine.core import ContinuousEngine
    from trlx_tpu.models.builder import build_causal_lm
    from trlx_tpu.models.transformer import make_kv_cache
    from trlx_tpu.ops.paged_kv import PagedSpec
    from trlx_tpu.ops.sampling import (
        GenerationConfig,
        apply_transition_mask,
        per_row_keys,
    )
    from trlx_tpu.ops.slot_refill import make_slot_refill_fns

    # builtin:bytes vocab: ids 0..255 bytes, 256 bos, 257 eos, 258 pad (=259)
    vocab, eos, pad = 259, 257, 258
    absorb_n = max(1, int(absorb_frac * 256))
    trans = np.ones((vocab, vocab), bool)
    trans[:absorb_n, :] = False
    trans[:absorb_n, eos] = True
    import jax.numpy as jnp

    tmask = jnp.asarray(trans)

    def adjust(step_out, logits):
        return apply_transition_mask(tmask, step_out["last_tokens"], logits)

    policy_extra = dict(
        num_layers=policy_layers,
        hidden_size=policy_hidden,
        num_heads=max(4, policy_hidden // 32),
        intermediate_size=4 * policy_hidden,
    )
    module, params, tcfg = build_causal_lm(
        ModelConfig(
            model_path="builtin:gpt2-test", model_extra_kwargs=dict(policy_extra)
        ),
        head="value",
    )

    def apply_fn(p, ids, **kw):
        return module.apply({"params": p}, ids, **kw)

    gen_config = GenerationConfig(
        max_new_tokens=max_new_tokens, eos_token_id=eos, pad_token_id=pad,
        do_sample=True, per_row_rng=True,
    )
    B, P, N = batch_size, prompt_len, max_new_tokens
    S = P + N
    rs = np.random.RandomState(seed)
    group_prompts = rs.randint(0, 200, (n_groups, P)).astype(np.int32)
    prompts = np.repeat(group_prompts, group_size, axis=0)  # GRPO-group shape
    masks = np.ones_like(prompts)
    n = prompts.shape[0]
    key_rng = jax.random.PRNGKey(seed)
    pass_keys = []
    for _ in range(passes + 1):  # +1 warmup wave
        key_rng, call = jax.random.split(key_rng)
        pass_keys.append(np.asarray(per_row_keys(call, n)))

    TB = -(-S // kv_block_size)
    results: Dict[str, Any] = {
        "config": dict(
            policy=policy_extra, batch_size=B, prompt_len=P,
            max_new_tokens=N, group_size=group_size, n_groups=n_groups,
            passes=passes, absorb_frac=absorb_frac,
            kv_block_size=kv_block_size, segment_len=segment_len,
        )
    }
    from trlx_tpu.ops.paged_kv import dense_kv_bytes
    from trlx_tpu.perf import lowered_costs

    harvests: Dict[str, Dict[int, Any]] = {}
    # dense reference, paged with the gather/scatter decode (the
    # bit-equivalence reference), and paged with the in-place Pallas
    # decode kernel + fused sampling (engine.decode_kernel: pallas)
    arms = (("dense", None), ("paged", "xla"), ("pallas", "pallas"))
    for mode, decode_kernel in arms:
        paged = (
            PagedSpec(block_size=kv_block_size, max_blocks=1 + 2 * B * TB)
            if decode_kernel is not None
            else None
        )
        fns = make_slot_refill_fns(
            apply_fn, lambda b, s: make_kv_cache(tcfg, b, s), B, P, gen_config,
            adjust_logits=adjust, segment_len=segment_len,
            params_example=params, paged=paged,
            decode_kernel=decode_kernel or "xla",
        )
        engine = ContinuousEngine(
            fns, params, pad, prefix_cache=(paged is not None)
        )

        def wave(k, got):
            engine.enqueue_prompts(prompts, masks, pass_keys[k])
            while engine.busy:
                for c in engine.step():
                    got[c.index] = (c.tokens.tobytes(), c.logprobs.tobytes())

        wave(0, {})  # warmup: compiles refill buckets + the segment program
        engine.begin_collection(params)  # same params: prefix cache stays warm
        got: Dict[int, Any] = {}
        t0 = time.time()
        for k in range(1, passes + 1):
            wave(k, got)
        dt = time.time() - t0
        harvests[mode] = got
        st = engine.stats
        gen_tokens = st.live_slot_steps
        results[mode] = {
            "seconds": round(dt, 3),
            "rollout_tokens_per_sec": round(gen_tokens / max(dt, 1e-9), 1),
            "slot_utilization": round(st.slot_utilization, 4),
            "prefill_tokens": int(st.prefill_tokens),
        }
        # XLA's compiled cost model for the segment-decode program each arm
        # actually ran — the program-level record of the gather tax (the
        # transient dense view exists in the gather arms' programs, not in
        # the kernel arm's)
        seg_costs = lowered_costs(
            fns.decode_segment.lower(params, engine.state)
        )
        results[mode]["decode_segment_program"] = {
            k: seg_costs[k]
            for k in ("flops", "bytes_accessed", "temp_bytes")
            if k in seg_costs
        }
        if paged is None:
            # the dense backend's persistent allocation IS its ceiling
            results[mode]["kv_cache_bytes"] = int(st.kv_cache_bytes)
        else:
            results[mode].update(
                # the full pool allocation and the live-token high-water
                # are DIFFERENT numbers — report both so the artifact
                # cannot be misread (the pool is deliberately
                # over-provisioned; the high-water is the memory claim)
                pool_bytes_allocated=int(st.kv_cache_bytes),
                kv_bytes_high_water=int(st.kv_bytes_high_water),
                kv_blocks_in_use=int(st.kv_blocks_in_use),
                kv_blocks_total=int(st.kv_blocks_total),
                prefix_hit_rate=round(st.prefix_hit_rate, 4),
                prefix_tokens_saved=int(st.prefix_tokens_saved),
                decode_kernel=decode_kernel,
                # analytic bytes of the transient dense view the gather
                # decode materializes per segment (and the kernel deletes)
                gather_view_bytes_per_segment=(
                    dense_kv_bytes(tcfg, B, S) if decode_kernel == "xla" else 0
                ),
            )

    assert harvests["dense"] == harvests["paged"], (
        "paged harvest diverged from dense — bit-parity contract broken"
    )
    assert harvests["pallas"] == harvests["dense"], (
        "pallas kernel harvest diverged from dense — bit-parity broken"
    )
    results["bit_identical"] = True
    # claim (1): paged KV high-water (live tokens) vs the dense ceiling —
    # identical for both paged arms (same allocator trace)
    results["kv_high_water_vs_dense"] = round(
        results["paged"]["kv_bytes_high_water"]
        / max(results["dense"]["kv_cache_bytes"], 1),
        4,
    )
    # claim (2): prefill tokens saved by prefix-cache hits
    results["prefill_tokens_saved_frac"] = round(
        1.0
        - results["paged"]["prefill_tokens"]
        / max(results["dense"]["prefill_tokens"], 1),
        4,
    )
    results["speedup"] = round(
        results["dense"]["seconds"] / max(results["paged"]["seconds"], 1e-9), 3
    )
    results["speedup_pallas"] = round(
        results["dense"]["seconds"] / max(results["pallas"]["seconds"], 1e-9), 3
    )
    import jax as _jax

    results["backend"] = _jax.default_backend()
    results["provenance"] = provenance()
    if _jax.default_backend() != "tpu":
        results["pallas_note"] = (
            "off-TPU the pallas arm runs under the Pallas interpreter "
            "(kernel body as sequential per-row XLA ops): its wall-clock "
            "measures the interpreter, not the kernel — the committed "
            "claims at CPU scale are bit-parity through the real kernel "
            "code path and the decode_segment_program accounting (the "
            "gather arms carry a transient dense view per segment, the "
            "kernel arm carries none)"
        )
    return results


def measure_engine_prefill(
    policy_layers: int = 8,
    policy_hidden: int = 128,
    batch_size: int = 8,
    long_prompt_len: int = 96,
    short_prompt_len: int = 8,
    max_new_tokens: int = 48,
    n_long: int = 12,
    n_short: int = 36,
    absorb_frac: float = 0.1,
    kv_block_size: int = 8,
    segment_len: int = 8,
    prefill_chunk: int = 16,
    seed: int = _SEED,
) -> Dict[str, Any]:
    """Paged-prefill A/B (ISSUE 14; docs/PERFORMANCE.md "Pallas kernels" +
    "Chunked prefill") on a mixed long/short-prompt workload — the
    long-sequence failure mode PipelineRL (arXiv:2509.19128) identifies:
    a long prompt's monolithic refill stalls every live decode slot.

    Five arms over identical per-row RNG streams, harvest asserted
    bit-identical across ALL arms inside this function (so every delta is
    bookkeeping/scheduling, never a workload change):

    - ``dense``: the dense per-slot reference engine;
    - ``gather``: paged backend, monolithic gather-prefill-scatter refill
      (the PR-6 baseline) — reports the analytic refill gather/scatter
      bytes its programs move;
    - ``gather_chunked``: the same compiled-XLA prefill under
      chunked-prefill scheduling (``engine.prefill_chunk``) — claim (b)
      is measured HERE, compiled program against compiled program: long
      prompts prefill one chunk per step between decode segments and the
      measured ``decode_stall_max`` drops;
    - ``pallas``: ``engine.prefill_kernel: pallas`` — the in-place
      prefill kernel; claim (a): refill gather/scatter bytes exactly 0;
    - ``pallas_chunked``: both together, the full ISSUE-14 configuration.

    Off-TPU the pallas arms run under the Pallas interpreter: their
    wall-clock (and hence their interpreter-mode stall seconds, dominated
    by per-call interpreter overhead) measures the interpreter, not the
    kernel — which is why claim (b) is pinned on the compiled gather
    arms; on chip, ``python -m trlx_tpu.benchmark engine-prefill`` is the
    one-command wall-clock A/B across all five (ROADMAP item 1).
    """
    import numpy as np

    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp

    from trlx_tpu.data.configs import ModelConfig
    from trlx_tpu.engine.core import ContinuousEngine
    from trlx_tpu.models.builder import build_causal_lm
    from trlx_tpu.models.transformer import make_kv_cache
    from trlx_tpu.ops.paged_kv import PagedSpec
    from trlx_tpu.ops.sampling import (
        GenerationConfig,
        apply_transition_mask,
        per_row_keys,
    )
    from trlx_tpu.ops.slot_refill import make_slot_refill_fns
    from trlx_tpu.perf import lowered_costs

    # builtin:bytes vocab: ids 0..255 bytes, 256 bos, 257 eos, 258 pad
    vocab, eos, pad = 259, 257, 258
    absorb_n = max(1, int(absorb_frac * 256))
    trans = np.ones((vocab, vocab), bool)
    trans[:absorb_n, :] = False
    trans[:absorb_n, eos] = True
    tmask = jnp.asarray(trans)

    def adjust(step_out, logits):
        return apply_transition_mask(tmask, step_out["last_tokens"], logits)

    policy_extra = dict(
        num_layers=policy_layers,
        hidden_size=policy_hidden,
        num_heads=max(4, policy_hidden // 32),
        intermediate_size=4 * policy_hidden,
    )
    module, params, tcfg = build_causal_lm(
        ModelConfig(
            model_path="builtin:gpt2-test", model_extra_kwargs=dict(policy_extra)
        ),
        head="value",
    )

    def apply_fn(p, ids, **kw):
        return module.apply({"params": p}, ids, **kw)

    gen_config = GenerationConfig(
        max_new_tokens=max_new_tokens, eos_token_id=eos, pad_token_id=pad,
        do_sample=True, per_row_rng=True,
    )
    B, P, N = batch_size, long_prompt_len, max_new_tokens
    S = P + N
    rs = np.random.RandomState(seed)
    # mixed workload, interleaved so long prompts keep arriving while short
    # rows decode: every long prefill event stalls live slots on the
    # monolithic arms
    prompts = np.full((n_long + n_short, P), pad, np.int32)
    masks = np.zeros_like(prompts)
    order = rs.permutation(n_long + n_short)
    for j, is_long in enumerate(order < n_long):
        width = long_prompt_len if is_long else short_prompt_len
        prompts[j, P - width:] = rs.randint(0, 200, width)
        masks[j, P - width:] = 1
    n = prompts.shape[0]
    keys = np.asarray(per_row_keys(jax.random.PRNGKey(seed), n))

    TB = -(-S // kv_block_size)
    results: Dict[str, Any] = {
        "config": dict(
            policy=policy_extra, batch_size=B,
            long_prompt_len=long_prompt_len,
            short_prompt_len=short_prompt_len, max_new_tokens=N,
            n_long=n_long, n_short=n_short, absorb_frac=absorb_frac,
            kv_block_size=kv_block_size, segment_len=segment_len,
            prefill_chunk=prefill_chunk,
        )
    }

    harvests: Dict[str, Dict[int, Any]] = {}
    arms = (
        ("dense", None, None, 0),
        ("gather", "xla", "xla", 0),
        ("gather_chunked", "xla", "xla", prefill_chunk),
        ("pallas", "xla", "pallas", 0),
        ("pallas_chunked", "xla", "pallas", prefill_chunk),
    )
    for mode, decode_kernel, prefill_kernel, chunk in arms:
        paged = (
            PagedSpec(block_size=kv_block_size, max_blocks=1 + 2 * B * TB)
            if decode_kernel is not None
            else None
        )
        fns = make_slot_refill_fns(
            apply_fn, lambda b, s: make_kv_cache(tcfg, b, s), B, P, gen_config,
            adjust_logits=adjust, segment_len=segment_len,
            params_example=params, paged=paged,
            decode_kernel=decode_kernel or "xla",
            prefill_kernel=prefill_kernel or "xla",
        )
        engine = ContinuousEngine(
            fns, params, pad, prefill_chunk=chunk
        )

        def wave(ks, got):
            engine.enqueue_prompts(prompts, masks, ks)
            while engine.busy:
                for c in engine.step():
                    got[c.index % n] = (c.tokens.tobytes(), c.logprobs.tobytes())

        wave(keys, {})  # warmup: compiles refill/chunk buckets + segments
        engine.begin_collection(params)
        got: Dict[int, Any] = {}
        t0 = time.time()
        wave(keys, got)
        dt = time.time() - t0
        harvests[mode] = got
        st = engine.stats
        results[mode] = {
            "seconds": round(dt, 3),
            "rollout_tokens_per_sec": round(
                st.live_slot_steps / max(dt, 1e-9), 1
            ),
            "slot_utilization": round(st.slot_utilization, 4),
            "prefill_tokens": int(st.prefill_tokens),
            "refill_prefills": int(st.refill_prefills),
            # the decode-stall gauges (one sample per prefill event that
            # ran while live decode slots waited): the scheduling claim
            "decode_stall_events": len(st.decode_stall_samples),
            "decode_stall_p50_s": round(st.decode_stall_p50, 5),
            "decode_stall_p95_s": round(st.decode_stall_p95, 5),
            "decode_stall_max_s": round(st.decode_stall_max, 5),
            "decode_stall_total_s": round(st.decode_stall_s, 4),
        }
        if paged is not None:
            results[mode].update(
                prefill_kernel=prefill_kernel,
                prefill_chunk=chunk,
                prefill_chunk_calls=int(st.prefill_chunk_calls),
                # the acceptance number: the transient dense-view bytes
                # the refill prefills move — 0 under the in-place kernel
                refill_gather_bytes=int(st.refill_gather_bytes),
                refill_scatter_bytes=int(st.refill_scatter_bytes),
            )
            # XLA's compiled cost model for the full-bucket cold refill
            # program each paged arm runs — the program-level record of
            # the gather/scatter tax (present in the gather arm's refill,
            # absent from the kernel arms')
            TBs = engine.state.cache.block_table.shape[1]
            refill_costs = lowered_costs(
                fns.refill_program(B).lower(
                    params,
                    jax.eval_shape(fns.init_state),
                    jax.ShapeDtypeStruct((B, P), jnp.int32),
                    jax.ShapeDtypeStruct((B, P), jnp.int32),
                    jax.ShapeDtypeStruct((B,), jnp.int32),
                    jax.ShapeDtypeStruct((B, 2), jnp.uint32),
                    jax.ShapeDtypeStruct((B, TBs), jnp.int32),
                )
            )
            results[mode]["refill_program"] = {
                k: refill_costs[k]
                for k in ("flops", "bytes_accessed", "temp_bytes")
                if k in refill_costs
            }

    for mode in ("gather", "gather_chunked", "pallas", "pallas_chunked"):
        assert harvests[mode] == harvests["dense"], (
            f"{mode} harvest diverged from dense — bit-parity contract broken"
        )
    results["bit_identical"] = True
    # claim (a): the refill gather/scatter tax, deleted by the kernel —
    # measured on the chunked pair (the monolithic gather arm's COLD
    # refills take the zero-cache shortcut and only scatter; its chunked
    # twin gathers the committed prefix every span, which is the cost the
    # serving-shaped workload actually pays)
    results["refill_bytes_baseline"] = int(
        results["gather_chunked"]["refill_gather_bytes"]
        + results["gather_chunked"]["refill_scatter_bytes"]
    )
    for mode in ("pallas", "pallas_chunked"):
        assert results[mode]["refill_gather_bytes"] == 0
        assert results[mode]["refill_scatter_bytes"] == 0
    # claim (b): chunked scheduling bounds the decode stall — compiled-XLA
    # arm against compiled-XLA arm (the pallas arms' interpreter-mode
    # wall-clock is per-call-overhead-dominated off-TPU, see pallas_note)
    results["decode_stall_max_ratio"] = round(
        results["gather_chunked"]["decode_stall_max_s"]
        / max(results["gather"]["decode_stall_max_s"], 1e-9),
        4,
    )
    import jax as _jax

    results["backend"] = _jax.default_backend()
    results["provenance"] = provenance()
    if _jax.default_backend() != "tpu":
        results["pallas_note"] = (
            "off-TPU the pallas arms run under the Pallas interpreter "
            "(kernel body as sequential per-row XLA ops): their "
            "wall-clock and stall seconds measure per-call interpreter "
            "overhead, not the kernel — the committed CPU-scale claims "
            "are (a) bit-parity through the real kernel code path with "
            "analytic refill gather/scatter bytes = 0, and (b) the stall "
            "reduction on the compiled-XLA gather vs gather_chunked "
            "pair; the day a TPU window opens, this command is the "
            "wall-clock A/B across all five arms"
        )
    return results


def measure_engine_spec(
    policy_layers: int = 8,
    policy_hidden: int = 128,
    draft_layers: int = 2,
    draft_hidden: int = 64,
    batch_size: int = 8,
    prompt_len: int = 16,
    max_new_tokens: int = 48,
    num_rollouts: int = 16,
    gamma: int = 4,
    absorb_frac: float = 0.08,
    kv_block_size: int = 8,
    segment_len: int = 4,
    seed: int = _SEED,
) -> Dict[str, Any]:
    """Engine A/B: plain paged decode segments vs speculative decode
    segments (``engine.speculative = gamma``, docs/PERFORMANCE.md
    "Speculative continuous batching") on a heterogeneous-length workload
    — ``num_rollouts`` prompts drained through ``batch_size`` slots with
    an absorbing transition mask (geometric lengths → refill churn).

    The plain and spec arms run DIFFERENT per-row streams by construction
    (the spec sampler advances the per-row key chains gamma+2 draws per
    round, the plain sampler one per token), so the in-benchmark equality
    assert is the spec contract itself: each spec arm's harvest is
    bit-identical, per row, to one solo batched ``generate_speculative``
    call over all ``num_rollouts`` rows — refills, block tables, and
    batch composition invisible (the standing tier-1 pin:
    ``tests/test_spec_engine.py``). The third arm (``spec_pallas``) runs
    the same speculative rounds over the Pallas kernels — the in-place
    paged prefill plus the multi-position verify kernel
    (``ops/paged_attention.py::paged_verify_attention``) — and is held to
    the same solo reference, pinning that the kernel composition changes
    no bit of the harvest.

    The committed claims (benchmarks/ENGINE_SPEC_cpu.json):

    - ``bit_identical_tokens``: spec-engine tokens/mask ≡ solo speculative
      run bitwise, logprobs/values to ``float_drift_max`` ≤ 1 f32 ulp
      (the refill program's dead logits head shifts XLA fusion at these
      widths; tier-1 pins FULL bitwise equality where both programs lower
      identically — tests/test_spec_engine.py);
    - ``spec.acceptance_rate`` > 0 on a real (smaller, differently
      seeded) draft against the target;
    - ``target_forwards_per_token``: the speculation win in
      backend-independent units — the plain segment runs one target
      forward per committed token (1.0 by construction), the spec
      segment runs one VERIFY forward per round over gamma+1 positions,
      i.e. ``live_rounds / committed`` = 1/tokens_per_round < 1.0;
    - the verify-program cost analysis: XLA compiled flops/bytes of both
      arms' segment programs — the spec segment's flops per invocation
      buy up to ``segment_len × (gamma+1)`` tokens where the plain
      segment's buy ``segment_len``;
    - program accounting: speculation swaps the refill + segment program
      pair, it does not ADD programs per bucket (the perf-budget entry
      ``gpt2_test_spec`` pins the same claim structurally).
    """
    import numpy as np

    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp

    from trlx_tpu.data.configs import ModelConfig
    from trlx_tpu.engine.core import ContinuousEngine
    from trlx_tpu.models.builder import build_causal_lm
    from trlx_tpu.models.transformer import make_kv_cache
    from trlx_tpu.ops.paged_kv import PagedSpec
    from trlx_tpu.ops.sampling import (
        GenerationConfig,
        apply_transition_mask,
        per_row_keys,
    )
    from trlx_tpu.ops.slot_refill import make_slot_refill_fns
    from trlx_tpu.ops.speculative import generate_speculative
    from trlx_tpu.perf import lowered_costs

    # builtin:bytes vocab: ids 0..255 bytes, 256 bos, 257 eos, 258 pad (=259)
    vocab, eos, pad = 259, 257, 258
    absorb_n = max(1, int(absorb_frac * 256))
    trans = np.ones((vocab, vocab), bool)
    trans[:absorb_n, :] = False
    trans[:absorb_n, eos] = True
    tmask = jnp.asarray(trans)

    def adjust(step_out, logits):
        return apply_transition_mask(tmask, step_out["last_tokens"], logits)

    policy_extra = dict(
        num_layers=policy_layers,
        hidden_size=policy_hidden,
        num_heads=max(4, policy_hidden // 32),
        intermediate_size=4 * policy_hidden,
    )
    draft_extra = dict(
        num_layers=draft_layers,
        hidden_size=draft_hidden,
        num_heads=max(4, draft_hidden // 32),
        intermediate_size=4 * draft_hidden,
    )
    # f32 compute: the bit-parity contract is pinned at f32 (same as the
    # tier-1 tests) — bf16 compute drifts at ulp scale between the
    # engine's and the solo sampler's lowerings (tokens unaffected; the
    # logprob bits differ), so a parity-ASSERTING artifact must not run it
    f32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)
    t_mod, t_params, tcfg = build_causal_lm(
        ModelConfig(
            model_path="builtin:gpt2-test",
            model_extra_kwargs=dict(policy_extra, **f32),
        ),
        head="value",
    )
    d_mod, d_params, dcfg = build_causal_lm(
        ModelConfig(
            model_path="builtin:gpt2-test",
            model_extra_kwargs=dict(draft_extra, **f32),
        ),
        head=None,
        seed=seed + 1,
    )

    def t_apply(p, ids, **kw):
        return t_mod.apply({"params": p}, ids, **kw)

    def d_apply(p, ids, **kw):
        return d_mod.apply({"params": p}, ids, **kw)

    gen_config = GenerationConfig(
        max_new_tokens=max_new_tokens, eos_token_id=eos, pad_token_id=pad,
        do_sample=True, per_row_rng=True,
    )
    B, P, N, G = batch_size, prompt_len, max_new_tokens, gamma
    rs = np.random.RandomState(seed)
    prompts = rs.randint(0, 200, (num_rollouts, P)).astype(np.int32)
    masks = np.ones_like(prompts)
    key_rng = jax.random.PRNGKey(seed)
    warm_key, run_key = jax.random.split(key_rng)
    warm_keys = np.asarray(per_row_keys(warm_key, num_rollouts))
    run_keys = np.asarray(per_row_keys(run_key, num_rollouts))

    results: Dict[str, Any] = {
        "config": dict(
            policy=policy_extra, draft=draft_extra, batch_size=B,
            prompt_len=P, max_new_tokens=N, num_rollouts=num_rollouts,
            gamma=G, absorb_frac=absorb_frac,
            kv_block_size=kv_block_size, segment_len=segment_len,
            compute_dtype="float32",
        )
    }

    harvests: Dict[str, Dict[int, Any]] = {}
    # three arms: the plain paged segments, the speculative segments over
    # the gather-reference kernels, and the speculative segments over the
    # Pallas kernels (decode_kernel + prefill_kernel: pallas — the spec
    # refill commits prompt K/V through the block table in place and the
    # verify forward runs the multi-position paged kernel,
    # ops/paged_attention.py::paged_verify_attention). Both spec arms
    # decode the SAME per-row streams, so both are parity-asserted against
    # the one solo run below.
    for mode in ("plain", "spec", "spec_pallas"):
        g = 0 if mode == "plain" else G
        S = P + N + g
        TB = -(-S // kv_block_size)
        paged = PagedSpec(block_size=kv_block_size, max_blocks=1 + 2 * B * TB)
        spec_kwargs = (
            dict(
                speculative=G, draft_apply=d_apply,
                init_draft_cache_fn=lambda b, s: make_kv_cache(dcfg, b, s),
                transition_mask=tmask,
            )
            if mode != "plain"
            # the plain arm composes the mask into adjust (the non-spec
            # convention); the spec arms pass it separately so draft AND
            # target are constrained inside the shared round
            else dict(adjust_logits=adjust)
        )
        if mode == "spec_pallas":
            spec_kwargs.update(decode_kernel="pallas", prefill_kernel="pallas")
        fns = make_slot_refill_fns(
            t_apply, lambda b, s: make_kv_cache(tcfg, b, s), B, P, gen_config,
            segment_len=segment_len, params_example=t_params, paged=paged,
            **spec_kwargs,
        )
        eng_params = t_params if mode == "plain" else (t_params, d_params)
        engine = ContinuousEngine(fns, eng_params, pad, prefix_cache=True)

        def wave(keys, got):
            engine.enqueue_prompts(prompts, masks, keys)
            while engine.busy:
                for c in engine.step():
                    # request indices run on across waves; fold back to
                    # the row number within this wave's enqueue order
                    got[c.index % num_rollouts] = {
                        "tokens": np.asarray(c.tokens),
                        "logprobs": np.asarray(c.logprobs),
                        "values": np.asarray(c.values),
                        "mask": np.asarray(c.mask),
                    }

        wave(warm_keys, {})  # warmup: compiles the refill buckets + segment
        engine.begin_collection(eng_params)
        got: Dict[int, Any] = {}
        t0 = time.time()
        wave(run_keys, got)
        dt = time.time() - t0
        harvests[mode] = got
        st = engine.stats
        m = st.metrics()
        results[mode] = {
            "seconds": round(dt, 3),
            "rollout_tokens_per_sec": round(
                st.live_slot_steps / max(dt, 1e-9), 1
            ),
            "slot_utilization": round(st.slot_utilization, 4),
            "prefill_tokens": int(st.prefill_tokens),
            "segment_program": {
                k: v
                for k, v in lowered_costs(
                    fns.decode_segment.lower(eng_params, engine.state)
                ).items()
                if k in ("flops", "bytes_accessed", "temp_bytes")
            },
        }
        if mode != "plain":
            results[mode].update(
                acceptance_rate=round(m["engine/spec_acceptance_rate"], 4),
                tokens_per_round=round(m["engine/spec_tokens_per_round"], 4),
                spec_rounds=int(m["rollout/spec_rounds"]),
                # verify forwards per committed token — the speculation
                # win in backend-independent units (plain = 1.0)
                target_forwards_per_token=round(
                    st.spec_live_rounds / max(st.spec_committed, 1), 4
                ),
                # which verify compute ran: the multi-position Pallas
                # paged kernel (in place) or the gather-reference shape
                verify_kernel=(
                    "pallas" if mode == "spec_pallas" else "xla"
                ),
            )

    # the in-benchmark bit-parity assert: the spec engine's harvest must
    # equal ONE solo batched speculative run of the same rows/keys — the
    # paged plumbing (refills, block tables, neighbors) is invisible
    solo = generate_speculative(
        t_apply, t_params, d_apply, d_params,
        lambda b, s: make_kv_cache(tcfg, b, s),
        lambda b, s: make_kv_cache(dcfg, b, s),
        jnp.asarray(prompts), jnp.asarray(masks), jnp.asarray(run_keys),
        gen_config, gamma=G, transition_mask=tmask,
    )
    float_drift = 0.0
    for arm in ("spec", "spec_pallas"):
        for i in range(num_rollouts):
            for field, solo_arr in (
                ("tokens", solo.response_tokens),
                ("mask", solo.response_mask),
            ):
                assert (
                    harvests[arm][i][field] == np.asarray(solo_arr)[i]
                ).all(), (
                    f"{arm} engine harvest diverged from solo speculative "
                    f"run (row {i}, {field}) — bit-parity contract broken"
                )
            for field, solo_arr in (
                ("logprobs", solo.response_logprobs),
                ("values", solo.response_values),
            ):
                d = float(
                    np.abs(harvests[arm][i][field] - np.asarray(solo_arr)[i]).max()
                )
                float_drift = max(float_drift, d)
                assert d <= 4e-6, (
                    f"{arm} engine {field} diverged from solo beyond ulp "
                    f"scale (row {i}, max {d:.3e}) — parity contract broken"
                )
    results["bit_identical_tokens"] = True
    # logprobs/values agree to ≤1 f32 ulp at these widths: the refill
    # program compiles separately from the solo sampler (its logits head
    # is dead code, which shifts XLA's last-layer fusion), so committed
    # prompt K/V can carry 1-ulp drift. The tier-1 tests pin FULL bitwise
    # equality — logprobs and values included — at the width where both
    # programs lower identically (tests/test_spec_engine.py); the round
    # function itself is shared code, not a reimplementation.
    results["float_drift_max"] = float_drift
    assert results["spec"]["acceptance_rate"] > 0.0, (
        "zero acceptance on a real draft/target pair"
    )
    # the pallas arm replays the same streams, so its acceptance matches
    assert (
        results["spec_pallas"]["acceptance_rate"]
        == results["spec"]["acceptance_rate"]
    ), "pallas verify kernel changed the acceptance trace"
    results["speedup"] = round(
        results["plain"]["seconds"] / max(results["spec"]["seconds"], 1e-9), 3
    )
    results["programs_note"] = (
        "speculation SWAPS the per-bucket program pair (refill, segment) "
        "for (spec refill, spec segment) — it adds zero programs per "
        "bucket; perf budgets gpt2_test_spec and gpt2_test_spec_kernel "
        "(benchmarks/perf_budgets.json) pin both programs' compiled costs "
        "for the gather-reference and Pallas-kernel compositions"
    )
    import jax as _jax

    results["backend"] = _jax.default_backend()
    results["provenance"] = provenance()
    if _jax.default_backend() != "tpu":
        results["cpu_note"] = (
            "CPU-scale run: per-segment dispatch overhead dominates the "
            "tiny models, so wall-clock speedup is NOT the claim — the "
            "committed claims are (a) parity of the spec engine harvest "
            "against the solo speculative sampler (tokens/mask bitwise, "
            "logprobs/values to float_drift_max ≤ 1 f32 ulp — see the "
            "bit_identical_tokens comment; tier-1 pins full bitwise "
            "equality), (b) acceptance "
            "> 0 on a real draft/target pair, and (c) "
            "target_forwards_per_token < 1.0 with the segment-program "
            "cost analysis: the verify forward's cost is amortized over "
            "tokens_per_round committed tokens. The spec_pallas arm runs "
            "the same rounds with the multi-position Pallas verify kernel "
            "+ in-place prefill — off-TPU under the Pallas interpreter, "
            "so its wall-clock measures the interpreter, not the kernel; "
            "its committed claim is bit-parity (same solo reference, same "
            "acceptance trace) through the real kernel code path. On "
            "chip, run: "
            "TRLX_TPU_PLATFORM=tpu python -m trlx_tpu.benchmark "
            "engine-spec --policy-layers 24 --policy-hidden 1024 "
            "--draft-layers 4 --draft-hidden 256 --batch-size 64 "
            "--max-new-tokens 256 --num-rollouts 512"
        )
    return results


def measure_loss_kernel(
    batch_size: int = 64,
    response_len: int = 128,
    block_rows: int = 8,
    rounds: int = 20,
    seed: int = _SEED,
) -> Dict[str, Any]:
    """Learner-step A/B: the staged XLA loss chain vs the fused Pallas
    kernel (``method.loss_kernel: pallas``, ops/fused_loss.py;
    docs/PERFORMANCE.md "Fused learner kernels") on a synthetic PPO batch
    of ``[batch_size, response_len]`` response windows with geometric
    per-row lengths.

    Three program measurements, all from XLA's compiled cost model
    (``trlx_tpu/perf.py::lowered_costs``) over identical runtime operands:

    - ``staged``: the three learner stages compiled as SEPARATE programs
      — GAE (``get_advantages_and_returns`` without whitening), masked
      whitening (``utils/stats.py::whiten``), and the clipped losses +
      stats (``PPOConfig.loss``) — so every ``[B, R]`` intermediate
      (advantages, returns, whitened advantages) crosses a program
      boundary through HBM. This is the per-stage round-trip accounting
      the fusion deletes;
    - ``xla``: the trainer's actual reference path
      (``fused_ppo_loss_reference``) in ONE jit — XLA already fuses what
      it can across the stages, but the GAE scan and the whitening
      reductions still materialize their ``[B, R]`` outputs;
    - ``fused``: the fused Pallas program (``fused_ppo_loss``) — each
      operand enters VMEM once, advantages/returns/whitening live and die
      on-chip.

    Both loss-and-stats and gradient (``d loss / d (logprobs, values)``)
    programs are measured, and the fused path is asserted BIT-IDENTICAL
    to the XLA reference in-function — loss, every stat, both grads —
    before any number is reported (jit-to-jit, every operand a runtime
    argument; see tests/test_fused_loss.py for why that harness rule
    matters). The committed acceptance number is the bytes-accessed
    reduction of ``fused`` against ``staged`` (and against ``xla``),
    plus the analytic inter-stage ``[B, R]`` round-trip bytes the fusion
    removes. Off-TPU the fused program runs under the Pallas interpreter,
    so its wall-clock measures the interpreter, not the kernel — see
    ``pallas_note`` in the artifact.
    """
    import numpy as np

    from trlx_tpu.trlx import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp

    from trlx_tpu.models.ppo import PPOConfig
    from trlx_tpu.ops.fused_loss import fused_ppo_loss, fused_ppo_loss_reference
    from trlx_tpu.ops.pallas_utils import has_pallas_tpu
    from trlx_tpu.perf import lowered_costs
    from trlx_tpu.utils.stats import whiten

    B, R = batch_size, response_len
    rs = np.random.RandomState(seed)
    # geometric per-row response lengths in [1, R]: the heterogeneous mask
    # shape the whitening/GAE epilogue sees in real collection
    lengths = np.clip(rs.geometric(p=4.0 / R, size=B), 1, R)
    mask = np.zeros((B, R), np.float32)
    for i, n in enumerate(lengths):
        mask[i, :n] = 1.0
    ops = (
        jnp.asarray(rs.randn(B, R).astype(np.float32) * 0.1),  # logprobs
        jnp.asarray(rs.randn(B, R).astype(np.float32)),  # values
        jnp.asarray(rs.randn(B, R).astype(np.float32) * 0.1),  # old_logprobs
        jnp.asarray(rs.randn(B, R).astype(np.float32)),  # old_values
        jnp.asarray(rs.randn(B, R).astype(np.float32) * 0.05),  # rewards
        jnp.asarray(mask),
    )
    method = PPOConfig(name="PPOConfig")

    def ref(*a):
        return fused_ppo_loss_reference(method, *a)

    def fus(*a):
        return fused_ppo_loss(method, *a, block_rows=block_rows)

    # the staged chain as three separately-compiled programs: the [B, R]
    # intermediates (advantages, returns, whitened advantages) cross HBM
    # at every boundary — the accounting the fused program deletes
    def stage_gae(old_values, rewards, m):
        return method.get_advantages_and_returns(
            old_values, rewards, m, use_whitening=False
        )

    def stage_whiten(advantages, m):
        return whiten(advantages, m)

    def stage_loss(logprobs, values, old_logprobs, old_values, adv, ret, m):
        return method.loss(
            logprobs=logprobs, values=values, old_logprobs=old_logprobs,
            old_values=old_values, advantages=adv, returns=ret, mask=m,
        )

    lp, v, olp, ov, rw, m = ops
    adv_raw, ret = jax.jit(stage_gae)(ov, rw, m)
    adv = jax.jit(stage_whiten)(adv_raw, m)

    def costs(lowered):
        c = lowered_costs(lowered)
        return {
            k: c[k]
            for k in ("flops", "bytes_accessed", "temp_bytes")
            if k in c
        }

    staged_stages = {
        "gae": costs(jax.jit(stage_gae).lower(ov, rw, m)),
        "whiten": costs(jax.jit(stage_whiten).lower(adv_raw, m)),
        "loss": costs(jax.jit(stage_loss).lower(lp, v, olp, ov, adv, ret, m)),
    }
    staged_total = {
        k: sum(s[k] for s in staged_stages.values() if k in s)
        for k in ("flops", "bytes_accessed", "temp_bytes")
    }

    def grad_fn(fn):
        return jax.jit(jax.grad(lambda *a: fn(*a)[0], argnums=(0, 1)))

    programs = {
        "staged": {"stages": staged_stages, "total": staged_total},
        "xla": {
            "loss": costs(jax.jit(ref).lower(*ops)),
            "loss_grad": costs(grad_fn(ref).lower(*ops)),
        },
        "fused": {
            "loss": costs(jax.jit(fus).lower(*ops)),
            "loss_grad": costs(grad_fn(fus).lower(*ops)),
        },
    }

    # bit-parity gate: no cost number is reported unless the fused program
    # is bit-identical to the reference on these exact operands
    rl, rstats = jax.jit(ref)(*ops)
    fl, fstats = jax.jit(fus)(*ops)
    assert jnp.array_equal(rl, fl), "fused loss != xla loss — parity broken"
    assert set(rstats) == set(fstats)
    for k in rstats:
        assert jnp.array_equal(rstats[k], fstats[k]), (
            f"fused stat {k} != xla — parity broken"
        )
    gr = grad_fn(ref)(*ops)
    gf = grad_fn(fus)(*ops)
    assert jnp.array_equal(gr[0], gf[0]) and jnp.array_equal(gr[1], gf[1]), (
        "fused grads != xla grads — parity broken"
    )

    # interpret-mode-caveated wall clock (meaningful on chip only)
    timings = {}
    for name, fn in (("xla", grad_fn(ref)), ("fused", grad_fn(fus))):
        jax.block_until_ready(fn(*ops))  # warmup/compile
        t0 = time.time()
        for _ in range(rounds):
            out = fn(*ops)
        jax.block_until_ready(out)
        timings[name] = round((time.time() - t0) / rounds, 6)

    f32 = 4
    results: Dict[str, Any] = {
        "config": dict(
            batch_size=B, response_len=R, block_rows=block_rows,
            rounds=rounds, seed=seed,
            response_len_mean=round(float(lengths.mean()), 2),
        ),
        "bit_identical": True,
        "programs": programs,
        # the acceptance numbers: one fused program instead of per-stage
        # [B, R] HBM round-trips
        "bytes_accessed_reduction_vs_staged": round(
            1.0
            - programs["fused"]["loss"]["bytes_accessed"]
            / max(staged_total["bytes_accessed"], 1.0),
            4,
        ),
        "bytes_accessed_reduction_vs_xla": round(
            1.0
            - programs["fused"]["loss"]["bytes_accessed"]
            / max(programs["xla"]["loss"]["bytes_accessed"], 1.0),
            4,
        ),
        # the [B, R] intermediates that cross program boundaries in the
        # staged chain (advantages, returns, whitened advantages — each
        # written by one stage and read by the next): exact arithmetic,
        # backend-independent
        "analytic_interstage_bytes": int(3 * 2 * B * R * f32),
        "accounting_note": (
            "the staged entry is the per-stage dispatch accounting "
            "(three programs, intermediates through HBM) — the round-trips "
            "the fusion deletes; the xla entry is the same chain in one "
            "jit, where the CPU cost model already credits XLA's own "
            "fusion, so fused-vs-xla measures interpret-lowering overhead "
            "(0 here: the fused program compiles to the identical cost) "
            "and the VMEM-residency win is an on-chip property the CPU "
            "cost model cannot see"
        ),
        "loss_grad_seconds_per_call": timings,
        "loss_kernel_pallas": float(has_pallas_tpu()),
    }
    import jax as _jax

    results["backend"] = _jax.default_backend()
    results["provenance"] = provenance()
    if _jax.default_backend() != "tpu":
        results["pallas_note"] = (
            "off-TPU the fused program runs under the Pallas interpreter "
            "(kernel body as sequential XLA ops): its wall-clock and its "
            "own cost-analysis numbers measure the interpreter lowering, "
            "not the Mosaic kernel — the committed CPU-scale claims are "
            "bit-parity (loss/stats/grads, asserted in-function) through "
            "the real kernel code path and the staged-chain bytes-accessed "
            "accounting (three separately-compiled stages round-trip the "
            "[B, R] intermediates through HBM; the fused path is one "
            "program). On chip, run: TRLX_TPU_PLATFORM=tpu python -m "
            "trlx_tpu.benchmark loss-kernel --batch-size 128 "
            "--response-len 512"
        )
    return results


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)
    run_p = sub.add_parser("run", help="run the benchmark suite")
    run_p.add_argument("--output-dir", required=True)
    run_p.add_argument("--tasks", nargs="*", default=None, choices=sorted(TASKS))
    run_p.add_argument("--scale", choices=("ci", "full"), default="ci")
    rep_p = sub.add_parser("report", help="compare two suite runs")
    rep_p.add_argument("run_a")
    rep_p.add_argument("run_b")
    rep_p.add_argument("--output", default=None, help="write markdown here (default stdout)")
    spec_p = sub.add_parser(
        "speculative", help="A/B rollout throughput: plain vs speculative decoding"
    )
    spec_p.add_argument("--output", default=None, help="write JSON here (default stdout)")
    spec_p.add_argument("--policy-layers", type=int, default=24)
    spec_p.add_argument("--policy-hidden", type=int, default=256)
    spec_p.add_argument("--gamma", type=int, default=4)
    spec_p.add_argument("--rounds", type=int, default=8)
    cb_p = sub.add_parser(
        "continuous-batching",
        help="A/B rollout collection: serial chunked decode vs slot-refill "
        "continuous batching on a heterogeneous-length workload",
    )
    cb_p.add_argument("--output", default=None, help="write JSON here (default stdout)")
    cb_p.add_argument("--policy-layers", type=int, default=8)
    cb_p.add_argument("--policy-hidden", type=int, default=128)
    cb_p.add_argument("--batch-size", type=int, default=16)
    cb_p.add_argument("--max-new-tokens", type=int, default=96)
    cb_p.add_argument("--num-rollouts", type=int, default=64)
    cb_p.add_argument("--absorb-frac", type=float, default=0.08)
    cb_p.add_argument("--segment-len", type=int, default=8)
    cb_p.add_argument("--rounds", type=int, default=3)
    ep_p = sub.add_parser(
        "engine-paged",
        help="A/B generation engine: dense per-slot KV vs paged block-pool "
        "KV + prefix cache on a shared-prefix (GRPO-group/eval) workload",
    )
    ep_p.add_argument("--output", default=None, help="write JSON here (default stdout)")
    ep_p.add_argument("--policy-layers", type=int, default=8)
    ep_p.add_argument("--policy-hidden", type=int, default=128)
    ep_p.add_argument("--batch-size", type=int, default=16)
    ep_p.add_argument("--prompt-len", type=int, default=32)
    ep_p.add_argument("--max-new-tokens", type=int, default=96)
    ep_p.add_argument("--group-size", type=int, default=8)
    ep_p.add_argument("--n-groups", type=int, default=8)
    ep_p.add_argument("--passes", type=int, default=2)
    ep_p.add_argument("--absorb-frac", type=float, default=0.08)
    ep_p.add_argument("--kv-block-size", type=int, default=8)
    ep_p.add_argument("--segment-len", type=int, default=8)
    es_p = sub.add_parser(
        "engine-spec",
        help="A/B generation engine: plain paged decode segments vs "
        "speculative (draft-propose + single-forward verify) decode "
        "segments on a heterogeneous-length workload",
    )
    es_p.add_argument("--output", default=None, help="write JSON here (default stdout)")
    es_p.add_argument("--policy-layers", type=int, default=8)
    es_p.add_argument("--policy-hidden", type=int, default=128)
    es_p.add_argument("--draft-layers", type=int, default=2)
    es_p.add_argument("--draft-hidden", type=int, default=64)
    es_p.add_argument("--batch-size", type=int, default=8)
    es_p.add_argument("--prompt-len", type=int, default=16)
    es_p.add_argument("--max-new-tokens", type=int, default=48)
    es_p.add_argument("--num-rollouts", type=int, default=16)
    es_p.add_argument("--gamma", type=int, default=4)
    es_p.add_argument("--absorb-frac", type=float, default=0.08)
    es_p.add_argument("--kv-block-size", type=int, default=8)
    es_p.add_argument("--segment-len", type=int, default=4)
    lk_p = sub.add_parser(
        "loss-kernel",
        help="A/B learner step: staged XLA GAE/whitening/loss chain vs "
        "the fused Pallas kernel (method.loss_kernel: pallas) — "
        "bit-parity asserted, compiled bytes-accessed recorded",
    )
    lk_p.add_argument("--output", default=None, help="write JSON here (default stdout)")
    lk_p.add_argument("--batch-size", type=int, default=64)
    lk_p.add_argument("--response-len", type=int, default=128)
    lk_p.add_argument("--block-rows", type=int, default=8)
    lk_p.add_argument("--rounds", type=int, default=20)
    pf_p = sub.add_parser(
        "engine-prefill",
        help="A/B paged prefill: gather-prefill-scatter vs the in-place "
        "Pallas prefill kernel + chunked-prefill scheduling on a mixed "
        "long/short-prompt workload",
    )
    pf_p.add_argument("--output", default=None, help="write JSON here (default stdout)")
    pf_p.add_argument("--policy-layers", type=int, default=8)
    pf_p.add_argument("--policy-hidden", type=int, default=128)
    pf_p.add_argument("--batch-size", type=int, default=8)
    pf_p.add_argument("--long-prompt-len", type=int, default=96)
    pf_p.add_argument("--short-prompt-len", type=int, default=8)
    pf_p.add_argument("--max-new-tokens", type=int, default=48)
    pf_p.add_argument("--n-long", type=int, default=12)
    pf_p.add_argument("--n-short", type=int, default=36)
    pf_p.add_argument("--absorb-frac", type=float, default=0.1)
    pf_p.add_argument("--kv-block-size", type=int, default=8)
    pf_p.add_argument("--segment-len", type=int, default=8)
    pf_p.add_argument("--prefill-chunk", type=int, default=16)
    args = parser.parse_args(argv)

    if args.cmd == "run":
        records = run_suite(args.output_dir, tasks=args.tasks, scale=args.scale)
        return 0 if all(r["rc"] == 0 for r in records) else 1
    if args.cmd == "speculative":
        result = measure_speculative(
            policy_layers=args.policy_layers,
            policy_hidden=args.policy_hidden,
            gamma=args.gamma,
            rounds=args.rounds,
        )
        text = json.dumps(result, indent=2)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        print(text)
        return 0
    if args.cmd == "continuous-batching":
        result = measure_continuous_batching(
            policy_layers=args.policy_layers,
            policy_hidden=args.policy_hidden,
            batch_size=args.batch_size,
            max_new_tokens=args.max_new_tokens,
            num_rollouts=args.num_rollouts,
            absorb_frac=args.absorb_frac,
            segment_len=args.segment_len,
            rounds=args.rounds,
        )
        text = json.dumps(result, indent=2)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        print(text)
        return 0
    if args.cmd == "engine-paged":
        result = measure_engine_paged(
            policy_layers=args.policy_layers,
            policy_hidden=args.policy_hidden,
            batch_size=args.batch_size,
            prompt_len=args.prompt_len,
            max_new_tokens=args.max_new_tokens,
            group_size=args.group_size,
            n_groups=args.n_groups,
            passes=args.passes,
            absorb_frac=args.absorb_frac,
            kv_block_size=args.kv_block_size,
            segment_len=args.segment_len,
        )
        text = json.dumps(result, indent=2)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        print(text)
        return 0
    if args.cmd == "engine-spec":
        result = measure_engine_spec(
            policy_layers=args.policy_layers,
            policy_hidden=args.policy_hidden,
            draft_layers=args.draft_layers,
            draft_hidden=args.draft_hidden,
            batch_size=args.batch_size,
            prompt_len=args.prompt_len,
            max_new_tokens=args.max_new_tokens,
            num_rollouts=args.num_rollouts,
            gamma=args.gamma,
            absorb_frac=args.absorb_frac,
            kv_block_size=args.kv_block_size,
            segment_len=args.segment_len,
        )
        text = json.dumps(result, indent=2)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        print(text)
        return 0
    if args.cmd == "loss-kernel":
        result = measure_loss_kernel(
            batch_size=args.batch_size,
            response_len=args.response_len,
            block_rows=args.block_rows,
            rounds=args.rounds,
        )
        text = json.dumps(result, indent=2)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        print(text)
        return 0
    if args.cmd == "engine-prefill":
        result = measure_engine_prefill(
            policy_layers=args.policy_layers,
            policy_hidden=args.policy_hidden,
            batch_size=args.batch_size,
            long_prompt_len=args.long_prompt_len,
            short_prompt_len=args.short_prompt_len,
            max_new_tokens=args.max_new_tokens,
            n_long=args.n_long,
            n_short=args.n_short,
            absorb_frac=args.absorb_frac,
            kv_block_size=args.kv_block_size,
            segment_len=args.segment_len,
            prefill_chunk=args.prefill_chunk,
        )
        text = json.dumps(result, indent=2)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        print(text)
        return 0
    text = compare_runs(args.run_a, args.run_b)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
