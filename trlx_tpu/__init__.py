"""trlx_tpu: a TPU-native (JAX/XLA/pjit/Pallas) RLHF fine-tuning framework.

Provides the capabilities of trlx (reference: ``trlx/trlx.py``) — online PPO
against a user reward function, offline ILQL from reward-labeled samples, and
SFT — re-designed TPU-first: Flax models sharded over a
``(data, pipe, fsdp, model, sequence)`` mesh, jitted KV-cached rollout
generation with on-device KL-to-reference, and fused pure-function losses
inside a pjit'd train step.
"""

__version__ = "0.4.0"

from trlx_tpu.trlx import train  # noqa: F401

__all__ = ["train", "__version__"]
