"""trlx_tpu: a TPU-native (JAX/XLA/pjit/Pallas) RLHF fine-tuning framework.

Provides the capabilities of trlx (reference: ``trlx/trlx.py``) — online PPO
against a user reward function, offline ILQL from reward-labeled samples, and
SFT — re-designed TPU-first: Flax models sharded over a
``(data, pipe, fsdp, model, sequence)`` mesh, jitted KV-cached rollout
generation with on-device KL-to-reference, and fused pure-function losses
inside a pjit'd train step.
"""

__version__ = "0.4.0"

__all__ = ["train", "__version__"]


def __getattr__(name):
    # Lazy (PEP 562): `trlx_tpu.train` pulls in the full jax/flax training
    # stack, but jax-free subpackages — graftlint (`trlx_tpu.analysis`,
    # which must run in lint-only CI with no ML deps), `trlx_tpu.native` —
    # must be importable without it.
    if name == "train":
        from trlx_tpu.trlx import train

        return train
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
