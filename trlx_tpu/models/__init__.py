"""Model layer: transformer backbones, heads, hydra reference branches, and
the method configs that carry the loss math (PPO/ILQL/SFT)."""
