"""DPO method: direct preference optimization — pure JAX loss.

Beyond the reference (trlx v0.6.0 ships PPO/ILQL/SFT): DPO (Rafailov et al.
2023) trains directly on preference pairs ``(prompt, chosen, rejected)``
without a reward model or rollouts — the implicit reward is
``β·(log π − log π_ref)`` and the objective is a logistic loss on the
chosen-vs-rejected reward margin. Fits this framework's offline path
(``trlx.train(samples=triples)``) exactly like ILQL/SFT do, and registers
through the same method registry (``trlx/data/method_configs.py:9-56``).
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.method_configs import MethodConfig, register_method
from trlx_tpu.utils import flatten_dict


@dataclass
@register_method("DPOConfig")
class DPOConfig(MethodConfig):
    """DPO hyperparameters.

    :param beta: inverse temperature of the implicit reward (typical 0.1-0.5).
    :param label_smoothing: conservative-DPO smoothing ε — assumes labels are
        flipped with probability ε (0 = standard DPO).
    :param reference_free: drop the reference terms (π_ref ≡ uniform);
        mostly for ablation.
    :param gen_kwargs: sampling settings for evaluation generation.
    """

    name: str = "DPOConfig"
    beta: float = 0.1
    label_smoothing: float = 0.0
    reference_free: bool = False
    gen_kwargs: Dict[str, Any] = field(default_factory=dict)
    # stream the vocab projection for completion logprobs in T-chunks of
    # this size instead of materializing [B, T, V] logits (0 = off); same
    # mechanism as SFTConfig.logit_chunk
    logit_chunk: int = 0

    def loss(
        self,
        policy_chosen_logps: jax.Array,  # [B] summed logprobs of chosen completions
        policy_rejected_logps: jax.Array,  # [B]
        ref_chosen_logps: jax.Array,  # [B] frozen-reference sums
        ref_rejected_logps: jax.Array,  # [B]
    ) -> Tuple[jax.Array, Dict[str, Any]]:
        pi_ratios = policy_chosen_logps - policy_rejected_logps
        if self.reference_free:
            ref_ratios = jnp.zeros_like(pi_ratios)
        else:
            ref_ratios = ref_chosen_logps - ref_rejected_logps
        logits = pi_ratios - ref_ratios  # the preference margin

        eps = self.label_smoothing
        losses = (
            -(1.0 - eps) * jax.nn.log_sigmoid(self.beta * logits)
            - eps * jax.nn.log_sigmoid(-self.beta * logits)
        )
        loss = losses.mean()

        chosen_rewards = self.beta * (policy_chosen_logps - ref_chosen_logps)
        rejected_rewards = self.beta * (policy_rejected_logps - ref_rejected_logps)
        dist = {}
        if self.dist_sketches:
            from trlx_tpu.observability.dynamics import loss_sketches

            # per-pair margins, [B] with no mask — the margin *distribution*
            # separates "uniformly confident" from "a few saturated pairs"
            dist = loss_sketches(
                {
                    "log_ratio": (logits, None),
                    "reward_margin": (chosen_rewards - rejected_rewards, None),
                }
            )
        stats = dict(
            **dist,
            losses=dict(total_loss=loss),
            rewards=dict(
                chosen=chosen_rewards.mean(),
                rejected=rejected_rewards.mean(),
                margin=(chosen_rewards - rejected_rewards).mean(),
                accuracy=(chosen_rewards > rejected_rewards).astype(jnp.float32).mean(),
            ),
            logps=dict(
                chosen=policy_chosen_logps.mean(),
                rejected=policy_rejected_logps.mean(),
            ),
        )
        return loss, flatten_dict(stats)
