"""HF (torch) checkpoint ⇄ trlx_tpu param-tree interop.

The reference wraps HF torch modules directly; here HF checkpoints are
*imported* into the native Flax parameter tree (and can be exported back via
``params_to_hf_state_dict``) — the interop equivalent of the reference's
sharded-checkpoint head merging (``trlx/models/modeling_base.py:142-184``,
``modeling_ppo.py:306-328``).

All converters are pure numpy: torch tensors → numpy → jax on first use.
Torch ``nn.Linear`` weights are [out, in] and transpose to Flax's [in, out];
GPT-2's Conv1D is already [in, out].
"""

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from trlx_tpu.models.transformer import TransformerConfig


class UnsupportedHFExport(ValueError):
    """Raised when an architecture has no transformers family mapping —
    the one 'skip HF export, keep the native msgpack' case. Genuine
    conversion bugs raise plain ValueError and must propagate."""


def _t(x: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(x.T)


def torch_state_dict_to_numpy(model) -> Dict[str, np.ndarray]:
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}


def _ln(sd, prefix) -> Dict[str, np.ndarray]:
    out = {"scale": sd[f"{prefix}.weight"]}
    if f"{prefix}.bias" in sd:
        out["bias"] = sd[f"{prefix}.bias"]
    return out


def _split_headmajor_qkv(w: np.ndarray, b, num_heads: int, head_dim: int):
    """Split a fused qkv with head-major interleave ([H, 3, D, E] rows) into
    q/k/v [E, H*D] kernels (+ biases). Used by GPT-NeoX and BLOOM."""
    E = w.shape[1]
    w = w.reshape(num_heads, 3, head_dim, E)
    outs = []
    for j in range(3):
        kernel = _t(w[:, j].reshape(num_heads * head_dim, E))
        bias = None
        if b is not None:
            bias = b.reshape(num_heads, 3, head_dim)[:, j].reshape(-1)
        outs.append((kernel, bias))
    return outs


def _proj(kernel: np.ndarray, bias=None) -> Dict[str, np.ndarray]:
    out = {"kernel": kernel}
    if bias is not None:
        out["bias"] = bias
    return out


def convert_gpt2(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
    p = "transformer."
    E = cfg.hidden_size
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd[p + "wte.weight"]},
        "wpe": {"embedding": sd[p + "wpe.weight"]},
        "ln_f": _ln(sd, p + "ln_f"),
    }
    for i in range(cfg.num_layers):
        lp = f"{p}h.{i}."
        w = sd[lp + "attn.c_attn.weight"]  # Conv1D [E, 3E]
        b = sd[lp + "attn.c_attn.bias"]
        q_w, k_w, v_w = w[:, :E], w[:, E : 2 * E], w[:, 2 * E :]
        q_b, k_b, v_b = b[:E], b[E : 2 * E], b[2 * E :]
        backbone[f"h_{i}"] = {
            "ln_attn": _ln(sd, lp + "ln_1"),
            "ln_mlp": _ln(sd, lp + "ln_2"),
            "attn": {
                "q_proj": _proj(q_w, q_b),
                "k_proj": _proj(k_w, k_b),
                "v_proj": _proj(v_w, v_b),
                "o_proj": _proj(sd[lp + "attn.c_proj.weight"], sd[lp + "attn.c_proj.bias"]),
            },
            "mlp": {
                "up_proj": _proj(sd[lp + "mlp.c_fc.weight"], sd[lp + "mlp.c_fc.bias"]),
                "down_proj": _proj(sd[lp + "mlp.c_proj.weight"], sd[lp + "mlp.c_proj.bias"]),
            },
        }
    return {"backbone": backbone}


def convert_llama(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
    p = "model."
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd[p + "embed_tokens.weight"]},
        "ln_f": {"scale": sd[p + "norm.weight"]},
        "lm_head": {"kernel": _t(sd["lm_head.weight"])},
    }
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        backbone[f"h_{i}"] = {
            "ln_attn": {"scale": sd[lp + "input_layernorm.weight"]},
            "ln_mlp": {"scale": sd[lp + "post_attention_layernorm.weight"]},
            "attn": {
                "q_proj": _proj(_t(sd[lp + "self_attn.q_proj.weight"])),
                "k_proj": _proj(_t(sd[lp + "self_attn.k_proj.weight"])),
                "v_proj": _proj(_t(sd[lp + "self_attn.v_proj.weight"])),
                "o_proj": _proj(_t(sd[lp + "self_attn.o_proj.weight"])),
            },
            "mlp": {
                "gate_proj": _proj(_t(sd[lp + "mlp.gate_proj.weight"])),
                "up_proj": _proj(_t(sd[lp + "mlp.up_proj.weight"])),
                "down_proj": _proj(_t(sd[lp + "mlp.down_proj.weight"])),
            },
        }
    return {"backbone": backbone}


def convert_mixtral(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
    """Mixtral (llama-style attention + sparse MoE MLP): per-expert
    ``w1``/``w3``/``w2`` Linears stack into the ``[E, ...]`` expert kernels
    and the router ``gate`` Linear becomes the fp32 router Dense. A declared
    ``sliding_window`` maps onto the native windowed-attention masking."""
    p = "model."
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd[p + "embed_tokens.weight"]},
        "ln_f": {"scale": sd[p + "norm.weight"]},
        "lm_head": {"kernel": _t(sd["lm_head.weight"])},
    }
    E = cfg.num_experts
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        ep = lp + "block_sparse_moe."
        backbone[f"h_{i}"] = {
            "ln_attn": {"scale": sd[lp + "input_layernorm.weight"]},
            "ln_mlp": {"scale": sd[lp + "post_attention_layernorm.weight"]},
            "attn": {
                "q_proj": _proj(_t(sd[lp + "self_attn.q_proj.weight"])),
                "k_proj": _proj(_t(sd[lp + "self_attn.k_proj.weight"])),
                "v_proj": _proj(_t(sd[lp + "self_attn.v_proj.weight"])),
                "o_proj": _proj(_t(sd[lp + "self_attn.o_proj.weight"])),
            },
            "mlp": {
                "router": {"kernel": _t(sd[ep + "gate.weight"])},
                "w_gate": np.stack(
                    [_t(sd[f"{ep}experts.{e}.w1.weight"]) for e in range(E)]
                ),
                "w_up": np.stack(
                    [_t(sd[f"{ep}experts.{e}.w3.weight"]) for e in range(E)]
                ),
                "w_down": np.stack(
                    [_t(sd[f"{ep}experts.{e}.w2.weight"]) for e in range(E)]
                ),
            },
        }
    return {"backbone": backbone}


def convert_gptneox(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
    p = "gpt_neox."
    D = cfg.dims_per_head
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd[p + "embed_in.weight"]},
        "ln_f": _ln(sd, p + "final_layer_norm"),
        "lm_head": {"kernel": _t(sd["embed_out.weight"])},
    }
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        (q_w, q_b), (k_w, k_b), (v_w, v_b) = _split_headmajor_qkv(
            sd[lp + "attention.query_key_value.weight"],
            sd.get(lp + "attention.query_key_value.bias"),
            cfg.num_heads,
            D,
        )
        backbone[f"h_{i}"] = {
            "ln_attn": _ln(sd, lp + "input_layernorm"),
            "ln_mlp": _ln(sd, lp + "post_attention_layernorm"),
            "attn": {
                "q_proj": _proj(q_w, q_b),
                "k_proj": _proj(k_w, k_b),
                "v_proj": _proj(v_w, v_b),
                "o_proj": _proj(_t(sd[lp + "attention.dense.weight"]), sd[lp + "attention.dense.bias"]),
            },
            "mlp": {
                "up_proj": _proj(_t(sd[lp + "mlp.dense_h_to_4h.weight"]), sd[lp + "mlp.dense_h_to_4h.bias"]),
                "down_proj": _proj(_t(sd[lp + "mlp.dense_4h_to_h.weight"]), sd[lp + "mlp.dense_4h_to_h.bias"]),
            },
        }
    return {"backbone": backbone}


def convert_gptj(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
    p = "transformer."
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd[p + "wte.weight"]},
        "ln_f": _ln(sd, p + "ln_f"),
        "lm_head": {"kernel": _t(sd["lm_head.weight"]), "bias": sd["lm_head.bias"]},
    }
    for i in range(cfg.num_layers):
        lp = f"{p}h.{i}."
        backbone[f"h_{i}"] = {
            "ln_attn": _ln(sd, lp + "ln_1"),
            "attn": {
                "q_proj": _proj(_t(sd[lp + "attn.q_proj.weight"])),
                "k_proj": _proj(_t(sd[lp + "attn.k_proj.weight"])),
                "v_proj": _proj(_t(sd[lp + "attn.v_proj.weight"])),
                "o_proj": _proj(_t(sd[lp + "attn.out_proj.weight"])),
            },
            "mlp": {
                "up_proj": _proj(_t(sd[lp + "mlp.fc_in.weight"]), sd[lp + "mlp.fc_in.bias"]),
                "down_proj": _proj(_t(sd[lp + "mlp.fc_out.weight"]), sd[lp + "mlp.fc_out.bias"]),
            },
        }
    return {"backbone": backbone}


def convert_opt(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
    p = "model.decoder."
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd[p + "embed_tokens.weight"]},
        "wpe": {"embedding": sd[p + "embed_positions.weight"]},
        "ln_f": _ln(sd, p + "final_layer_norm"),
    }
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        backbone[f"h_{i}"] = {
            "ln_attn": _ln(sd, lp + "self_attn_layer_norm"),
            "ln_mlp": _ln(sd, lp + "final_layer_norm"),
            "attn": {
                "q_proj": _proj(_t(sd[lp + "self_attn.q_proj.weight"]), sd[lp + "self_attn.q_proj.bias"]),
                "k_proj": _proj(_t(sd[lp + "self_attn.k_proj.weight"]), sd[lp + "self_attn.k_proj.bias"]),
                "v_proj": _proj(_t(sd[lp + "self_attn.v_proj.weight"]), sd[lp + "self_attn.v_proj.bias"]),
                "o_proj": _proj(_t(sd[lp + "self_attn.out_proj.weight"]), sd[lp + "self_attn.out_proj.bias"]),
            },
            "mlp": {
                "up_proj": _proj(_t(sd[lp + "fc1.weight"]), sd[lp + "fc1.bias"]),
                "down_proj": _proj(_t(sd[lp + "fc2.weight"]), sd[lp + "fc2.bias"]),
            },
        }
    return {"backbone": backbone}


def convert_bloom(sd: Dict[str, np.ndarray], cfg: TransformerConfig) -> Dict[str, Any]:
    p = "transformer."
    D = cfg.dims_per_head
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd[p + "word_embeddings.weight"]},
        "emb_ln": _ln(sd, p + "word_embeddings_layernorm"),
        "ln_f": _ln(sd, p + "ln_f"),
    }
    for i in range(cfg.num_layers):
        lp = f"{p}h.{i}."
        (q_w, q_b), (k_w, k_b), (v_w, v_b) = _split_headmajor_qkv(
            sd[lp + "self_attention.query_key_value.weight"],
            sd.get(lp + "self_attention.query_key_value.bias"),
            cfg.num_heads,
            D,
        )
        backbone[f"h_{i}"] = {
            "ln_attn": _ln(sd, lp + "input_layernorm"),
            "ln_mlp": _ln(sd, lp + "post_attention_layernorm"),
            "attn": {
                "q_proj": _proj(q_w, q_b),
                "k_proj": _proj(k_w, k_b),
                "v_proj": _proj(v_w, v_b),
                "o_proj": _proj(
                    _t(sd[lp + "self_attention.dense.weight"]), sd[lp + "self_attention.dense.bias"]
                ),
            },
            "mlp": {
                "up_proj": _proj(_t(sd[lp + "mlp.dense_h_to_4h.weight"]), sd[lp + "mlp.dense_h_to_4h.bias"]),
                "down_proj": _proj(_t(sd[lp + "mlp.dense_4h_to_h.weight"]), sd[lp + "mlp.dense_4h_to_h.bias"]),
            },
        }
    return {"backbone": backbone}


CONVERTERS: Dict[str, Callable] = {
    "gpt2": convert_gpt2,
    "llama": convert_llama,
    "gpt_neox": convert_gptneox,
    "gptj": convert_gptj,
    "opt": convert_opt,
    "bloom": convert_bloom,
    "mistral": convert_llama,  # identical key layout (llama + sliding window)
    "mixtral": convert_mixtral,
}


def config_from_hf(hf_config) -> TransformerConfig:
    """Map a transformers config object to a :class:`TransformerConfig`."""
    mt = hf_config.model_type
    if mt == "gpt2":
        return TransformerConfig(
            model_type=mt,
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
            max_position_embeddings=hf_config.n_positions,
            position_scheme="learned",
            activation="gelu_new",
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
        )
    if mt in ("llama", "mistral"):
        # mistral IS the llama mapping + head_dim override + sliding window
        # (both getattrs are None-safe on LlamaConfig)
        return TransformerConfig(
            model_type=mt,
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            head_dim=getattr(hf_config, "head_dim", None),
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            position_scheme="rotary",
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            norm="rmsnorm",
            layer_norm_epsilon=hf_config.rms_norm_eps,
            activation="silu",
            attn_bias=False,
            mlp_bias=False,
            tie_word_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
            sliding_window=getattr(hf_config, "sliding_window", None),
        )
    if mt == "mixtral":
        return TransformerConfig(
            model_type=mt,
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            position_scheme="rotary",
            rope_theta=getattr(hf_config, "rope_theta", 1e6),
            norm="rmsnorm",
            layer_norm_epsilon=hf_config.rms_norm_eps,
            activation="silu",
            attn_bias=False,
            mlp_bias=False,
            tie_word_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
            num_experts=hf_config.num_local_experts,
            num_experts_per_tok=hf_config.num_experts_per_tok,
            router_aux_coef=getattr(hf_config, "router_aux_loss_coef", 0.01),
            moe_group_size=512,
            sliding_window=getattr(hf_config, "sliding_window", None),
            # HF Mixtral routes with no capacity bound (dense gather); a
            # capacity factor of E makes the einsum dispatch drop-free by
            # construction (even if every token picked the same expert), so
            # imported checkpoints reproduce HF logits exactly. Lower it for
            # training throughput at the cost of overflow-token drops.
            moe_capacity_factor=float(hf_config.num_local_experts),
        )
    if mt == "gpt_neox":
        head_dim = hf_config.hidden_size // hf_config.num_attention_heads
        return TransformerConfig(
            model_type=mt,
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            position_scheme="rotary",
            rotary_dim=int(head_dim * hf_config.rotary_pct),
            rope_theta=getattr(hf_config, "rotary_emb_base", 10000.0),
            activation="gelu",
            parallel_residual=bool(hf_config.use_parallel_residual),
            shared_ln=False,
            layer_norm_epsilon=hf_config.layer_norm_eps,
            tie_word_embeddings=False,
        )
    if mt == "gptj":
        return TransformerConfig(
            model_type=mt,
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            intermediate_size=hf_config.n_inner or 4 * hf_config.n_embd,
            max_position_embeddings=hf_config.n_positions,
            position_scheme="rotary",
            rotary_dim=hf_config.rotary_dim,
            activation="gelu_new",
            parallel_residual=True,
            shared_ln=True,
            attn_bias=False,
            qkv_bias=False,
            mlp_bias=True,
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
            tie_word_embeddings=False,
            lm_head_bias=True,
        )
    if mt == "opt":
        return TransformerConfig(
            model_type=mt,
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers,
            num_heads=hf_config.num_attention_heads,
            intermediate_size=hf_config.ffn_dim,
            max_position_embeddings=hf_config.max_position_embeddings,
            position_scheme="learned",
            pos_offset=2,
            activation=hf_config.activation_function,
            tie_word_embeddings=True,
        )
    if mt == "bloom":
        return TransformerConfig(
            model_type=mt,
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            num_layers=hf_config.n_layer,
            num_heads=hf_config.n_head,
            intermediate_size=4 * hf_config.hidden_size,
            max_position_embeddings=2048,
            position_scheme="alibi",
            activation="gelu",
            embedding_layernorm=True,
            layer_norm_epsilon=hf_config.layer_norm_epsilon,
            tie_word_embeddings=True,
        )
    raise ValueError(f"Unsupported HF model type for causal import: {mt}")


def params_from_hf(model, cfg: TransformerConfig = None) -> Tuple[Dict[str, Any], TransformerConfig]:
    """Convert a loaded HF torch model into (params, config)."""
    if cfg is None:
        cfg = config_from_hf(model.config)
    sd = torch_state_dict_to_numpy(model)
    converter = CONVERTERS[model.config.model_type]
    return converter(sd, cfg), cfg


def load_pretrained(path: str) -> Tuple[Dict[str, Any], TransformerConfig]:
    """Load an HF checkpoint from a local path into (params, config)."""
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_config = AutoConfig.from_pretrained(path)
    model = AutoModelForCausalLM.from_pretrained(path)
    return params_from_hf(model, config_from_hf(hf_config))


# ---------------------------------------------------------------------------
# seq2seq (T5 family) import — reference wraps HF T5 for its seq2seq path
# (``trlx/models/modeling_ppo.py:948-1222``); here the torch checkpoint is
# converted into the T5Transformer param tree.
# ---------------------------------------------------------------------------


def _t5_attn(sd, prefix) -> Dict[str, Any]:
    return {
        "q_proj": _proj(_t(sd[prefix + ".q.weight"])),
        "k_proj": _proj(_t(sd[prefix + ".k.weight"])),
        "v_proj": _proj(_t(sd[prefix + ".v.weight"])),
        "o_proj": _proj(_t(sd[prefix + ".o.weight"])),
    }


def _t5_mlp(sd, prefix, gated: bool) -> Dict[str, Any]:
    if gated:
        return {
            "gate_proj": _proj(_t(sd[prefix + ".wi_0.weight"])),
            "up_proj": _proj(_t(sd[prefix + ".wi_1.weight"])),
            "down_proj": _proj(_t(sd[prefix + ".wo.weight"])),
        }
    return {
        "up_proj": _proj(_t(sd[prefix + ".wi.weight"])),
        "down_proj": _proj(_t(sd[prefix + ".wo.weight"])),
    }


def convert_t5(sd: Dict[str, np.ndarray], cfg) -> Dict[str, Any]:
    """HF T5/Flan-T5 state dict → T5Transformer param tree."""
    gated = cfg.activation == "gated-gelu"
    backbone: Dict[str, Any] = {
        "wte": {"embedding": sd["shared.weight"]},
        "enc_rel_bias": {
            "rel_bias": {
                "embedding": sd[
                    "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
                ]
            }
        },
        "dec_rel_bias": {
            "rel_bias": {
                "embedding": sd[
                    "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
                ]
            }
        },
        "enc_ln_f": {"scale": sd["encoder.final_layer_norm.weight"]},
        "dec_ln_f": {"scale": sd["decoder.final_layer_norm.weight"]},
    }
    for i in range(cfg.num_layers):
        lp = f"encoder.block.{i}."
        backbone[f"enc_{i}"] = {
            "ln_self": {"scale": sd[lp + "layer.0.layer_norm.weight"]},
            "self_attn": _t5_attn(sd, lp + "layer.0.SelfAttention"),
            "ln_mlp": {"scale": sd[lp + "layer.1.layer_norm.weight"]},
            "mlp": _t5_mlp(sd, lp + "layer.1.DenseReluDense", gated),
        }
    for i in range(cfg.num_decoder_layers):
        lp = f"decoder.block.{i}."
        backbone[f"dec_{i}"] = {
            "ln_self": {"scale": sd[lp + "layer.0.layer_norm.weight"]},
            "self_attn": _t5_attn(sd, lp + "layer.0.SelfAttention"),
            "ln_cross": {"scale": sd[lp + "layer.1.layer_norm.weight"]},
            "cross_attn": _t5_attn(sd, lp + "layer.1.EncDecAttention"),
            "ln_mlp": {"scale": sd[lp + "layer.2.layer_norm.weight"]},
            "mlp": _t5_mlp(sd, lp + "layer.2.DenseReluDense", gated),
        }
    if not cfg.tie_word_embeddings:
        backbone["lm_head"] = _proj(_t(sd["lm_head.weight"]))
    return {"backbone": backbone}


def seq2seq_config_from_hf(hf_config):
    """Map a transformers T5Config to :class:`Seq2SeqConfig`."""
    from trlx_tpu.models.seq2seq import Seq2SeqConfig

    if hf_config.model_type not in ("t5", "mt5"):
        raise ValueError(f"Unsupported HF model type for seq2seq import: {hf_config.model_type}")
    act = hf_config.feed_forward_proj
    if act not in ("relu", "gated-gelu"):
        raise ValueError(
            f"Unsupported T5 feed_forward_proj '{act}' (supported: relu, gated-gelu)"
        )
    return Seq2SeqConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.d_model,
        num_layers=hf_config.num_layers,
        num_decoder_layers=hf_config.num_decoder_layers,
        num_heads=hf_config.num_heads,
        head_dim=hf_config.d_kv,
        intermediate_size=hf_config.d_ff,
        relative_attention_num_buckets=hf_config.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(
            hf_config, "relative_attention_max_distance", 128
        ),
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        activation=act,
        tie_word_embeddings=bool(hf_config.tie_word_embeddings),
        decoder_start_token_id=hf_config.decoder_start_token_id or 0,
        pad_token_id=hf_config.pad_token_id or 0,
    )


def seq2seq_params_from_hf(model, cfg=None) -> Tuple[Dict[str, Any], Any]:
    if cfg is None:
        cfg = seq2seq_config_from_hf(model.config)
    sd = torch_state_dict_to_numpy(model)
    return convert_t5(sd, cfg), cfg


def load_pretrained_seq2seq(path: str):
    from transformers import AutoConfig, AutoModelForSeq2SeqLM

    hf_config = AutoConfig.from_pretrained(path)
    model = AutoModelForSeq2SeqLM.from_pretrained(path)
    return seq2seq_params_from_hf(model, seq2seq_config_from_hf(hf_config))


# ---------------------------------------------------------------------------
# Export: trlx_tpu param tree → HF (torch) checkpoint directory.
#
# Inverse of the import converters above, including the reference's head
# merging semantics: value/ILQL head weights are folded into the state dict
# under ``v_head.`` / ``ilql_heads.`` prefixes with the reference's own
# torch module names (``trlx/models/modeling_ppo.py:306-328``,
# ``modeling_ilql.py:322-344``), so a checkpoint exported here loads both in
# plain ``transformers`` (heads ignored) and in reference trlx (heads
# re-split).
# ---------------------------------------------------------------------------


def _fuse_headmajor_qkv(attn: Dict[str, Any], num_heads: int, head_dim: int):
    """Inverse of :func:`_split_headmajor_qkv`: q/k/v kernels [E, H*D] →
    fused [3*H*D, E] torch weight with head-major interleave (+ fused bias)."""
    E = attn["q_proj"]["kernel"].shape[0]
    ws = []
    for name in ("q_proj", "k_proj", "v_proj"):
        ws.append(_t(np.asarray(attn[name]["kernel"])).reshape(num_heads, head_dim, E))
    w = np.stack(ws, axis=1).reshape(num_heads * 3 * head_dim, E)
    b = None
    if "bias" in attn["q_proj"]:
        bs = [
            np.asarray(attn[name]["bias"]).reshape(num_heads, head_dim)
            for name in ("q_proj", "k_proj", "v_proj")
        ]
        b = np.stack(bs, axis=1).reshape(-1)
    return w, b


def _put_ln(sd: Dict[str, np.ndarray], prefix: str, ln: Dict[str, Any]) -> None:
    sd[f"{prefix}.weight"] = np.asarray(ln["scale"])
    if "bias" in ln:
        sd[f"{prefix}.bias"] = np.asarray(ln["bias"])


def _put_linear(sd, prefix, proj, transpose=True) -> None:
    kernel = np.asarray(proj["kernel"])
    sd[f"{prefix}.weight"] = _t(kernel) if transpose else kernel
    if "bias" in proj:
        sd[f"{prefix}.bias"] = np.asarray(proj["bias"])


def export_gpt2(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    p = "transformer."
    sd: Dict[str, np.ndarray] = {
        p + "wte.weight": np.asarray(backbone["wte"]["embedding"]),
        p + "wpe.weight": np.asarray(backbone["wpe"]["embedding"]),
    }
    _put_ln(sd, p + "ln_f", backbone["ln_f"])
    for i in range(cfg.num_layers):
        lp = f"{p}h.{i}."
        h = backbone[f"h_{i}"]
        _put_ln(sd, lp + "ln_1", h["ln_attn"])
        _put_ln(sd, lp + "ln_2", h["ln_mlp"])
        attn = h["attn"]
        # Conv1D layout [in, out]: our kernels go in untransposed
        sd[lp + "attn.c_attn.weight"] = np.concatenate(
            [np.asarray(attn[k]["kernel"]) for k in ("q_proj", "k_proj", "v_proj")], axis=1
        )
        sd[lp + "attn.c_attn.bias"] = np.concatenate(
            [np.asarray(attn[k]["bias"]) for k in ("q_proj", "k_proj", "v_proj")]
        )
        _put_linear(sd, lp + "attn.c_proj", attn["o_proj"], transpose=False)
        _put_linear(sd, lp + "mlp.c_fc", h["mlp"]["up_proj"], transpose=False)
        _put_linear(sd, lp + "mlp.c_proj", h["mlp"]["down_proj"], transpose=False)
    sd["lm_head.weight"] = sd[p + "wte.weight"]  # tied
    return sd


def export_llama(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    p = "model."
    sd: Dict[str, np.ndarray] = {
        p + "embed_tokens.weight": np.asarray(backbone["wte"]["embedding"]),
        p + "norm.weight": np.asarray(backbone["ln_f"]["scale"]),
    }
    if cfg.tie_word_embeddings:
        sd["lm_head.weight"] = sd[p + "embed_tokens.weight"]
    else:
        sd["lm_head.weight"] = _t(np.asarray(backbone["lm_head"]["kernel"]))
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        h = backbone[f"h_{i}"]
        sd[lp + "input_layernorm.weight"] = np.asarray(h["ln_attn"]["scale"])
        sd[lp + "post_attention_layernorm.weight"] = np.asarray(h["ln_mlp"]["scale"])
        for ours, theirs in (
            ("q_proj", "self_attn.q_proj"),
            ("k_proj", "self_attn.k_proj"),
            ("v_proj", "self_attn.v_proj"),
            ("o_proj", "self_attn.o_proj"),
        ):
            _put_linear(sd, lp + theirs, h["attn"][ours])
        for ours, theirs in (
            ("gate_proj", "mlp.gate_proj"),
            ("up_proj", "mlp.up_proj"),
            ("down_proj", "mlp.down_proj"),
        ):
            _put_linear(sd, lp + theirs, h["mlp"][ours])
    return sd


def export_gptneox(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    p = "gpt_neox."
    sd: Dict[str, np.ndarray] = {
        p + "embed_in.weight": np.asarray(backbone["wte"]["embedding"]),
        "embed_out.weight": _t(np.asarray(backbone["lm_head"]["kernel"])),
    }
    _put_ln(sd, p + "final_layer_norm", backbone["ln_f"])
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        h = backbone[f"h_{i}"]
        _put_ln(sd, lp + "input_layernorm", h["ln_attn"])
        _put_ln(sd, lp + "post_attention_layernorm", h["ln_mlp"])
        w, b = _fuse_headmajor_qkv(h["attn"], cfg.num_heads, cfg.dims_per_head)
        sd[lp + "attention.query_key_value.weight"] = w
        if b is not None:
            sd[lp + "attention.query_key_value.bias"] = b
        _put_linear(sd, lp + "attention.dense", h["attn"]["o_proj"])
        _put_linear(sd, lp + "mlp.dense_h_to_4h", h["mlp"]["up_proj"])
        _put_linear(sd, lp + "mlp.dense_4h_to_h", h["mlp"]["down_proj"])
    return sd


def export_gptj(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    p = "transformer."
    sd: Dict[str, np.ndarray] = {
        p + "wte.weight": np.asarray(backbone["wte"]["embedding"]),
        "lm_head.weight": _t(np.asarray(backbone["lm_head"]["kernel"])),
        "lm_head.bias": np.asarray(backbone["lm_head"]["bias"]),
    }
    _put_ln(sd, p + "ln_f", backbone["ln_f"])
    for i in range(cfg.num_layers):
        lp = f"{p}h.{i}."
        h = backbone[f"h_{i}"]
        _put_ln(sd, lp + "ln_1", h["ln_attn"])
        for ours, theirs in (
            ("q_proj", "attn.q_proj"),
            ("k_proj", "attn.k_proj"),
            ("v_proj", "attn.v_proj"),
            ("o_proj", "attn.out_proj"),
        ):
            _put_linear(sd, lp + theirs, h["attn"][ours])
        _put_linear(sd, lp + "mlp.fc_in", h["mlp"]["up_proj"])
        _put_linear(sd, lp + "mlp.fc_out", h["mlp"]["down_proj"])
    return sd


def export_opt(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    p = "model.decoder."
    sd: Dict[str, np.ndarray] = {
        p + "embed_tokens.weight": np.asarray(backbone["wte"]["embedding"]),
        p + "embed_positions.weight": np.asarray(backbone["wpe"]["embedding"]),
        "lm_head.weight": np.asarray(backbone["wte"]["embedding"]),  # tied
    }
    _put_ln(sd, p + "final_layer_norm", backbone["ln_f"])
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        h = backbone[f"h_{i}"]
        _put_ln(sd, lp + "self_attn_layer_norm", h["ln_attn"])
        _put_ln(sd, lp + "final_layer_norm", h["ln_mlp"])
        for ours, theirs in (
            ("q_proj", "self_attn.q_proj"),
            ("k_proj", "self_attn.k_proj"),
            ("v_proj", "self_attn.v_proj"),
            ("o_proj", "self_attn.out_proj"),
        ):
            _put_linear(sd, lp + theirs, h["attn"][ours])
        _put_linear(sd, lp + "fc1", h["mlp"]["up_proj"])
        _put_linear(sd, lp + "fc2", h["mlp"]["down_proj"])
    return sd


def export_bloom(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    p = "transformer."
    sd: Dict[str, np.ndarray] = {
        p + "word_embeddings.weight": np.asarray(backbone["wte"]["embedding"]),
        "lm_head.weight": np.asarray(backbone["wte"]["embedding"]),  # tied
    }
    _put_ln(sd, p + "word_embeddings_layernorm", backbone["emb_ln"])
    _put_ln(sd, p + "ln_f", backbone["ln_f"])
    for i in range(cfg.num_layers):
        lp = f"{p}h.{i}."
        h = backbone[f"h_{i}"]
        _put_ln(sd, lp + "input_layernorm", h["ln_attn"])
        _put_ln(sd, lp + "post_attention_layernorm", h["ln_mlp"])
        w, b = _fuse_headmajor_qkv(h["attn"], cfg.num_heads, cfg.dims_per_head)
        sd[lp + "self_attention.query_key_value.weight"] = w
        if b is not None:
            sd[lp + "self_attention.query_key_value.bias"] = b
        _put_linear(sd, lp + "self_attention.dense", h["attn"]["o_proj"])
        _put_linear(sd, lp + "mlp.dense_h_to_4h", h["mlp"]["up_proj"])
        _put_linear(sd, lp + "mlp.dense_4h_to_h", h["mlp"]["down_proj"])
    return sd


def export_mixtral(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_mixtral`: expert kernels unstack into the
    per-expert ``w1``/``w3``/``w2`` Linears of MixtralForCausalLM."""
    p = "model."
    sd: Dict[str, np.ndarray] = {
        p + "embed_tokens.weight": np.asarray(backbone["wte"]["embedding"]),
        p + "norm.weight": np.asarray(backbone["ln_f"]["scale"]),
        "lm_head.weight": (
            np.asarray(backbone["wte"]["embedding"])
            if cfg.tie_word_embeddings
            else _t(np.asarray(backbone["lm_head"]["kernel"]))
        ),
    }
    for i in range(cfg.num_layers):
        lp = f"{p}layers.{i}."
        ep = lp + "block_sparse_moe."
        h = backbone[f"h_{i}"]
        sd[lp + "input_layernorm.weight"] = np.asarray(h["ln_attn"]["scale"])
        sd[lp + "post_attention_layernorm.weight"] = np.asarray(h["ln_mlp"]["scale"])
        for ours, theirs in (
            ("q_proj", "self_attn.q_proj"),
            ("k_proj", "self_attn.k_proj"),
            ("v_proj", "self_attn.v_proj"),
            ("o_proj", "self_attn.o_proj"),
        ):
            _put_linear(sd, lp + theirs, h["attn"][ours])
        mlp = h["mlp"]
        sd[ep + "gate.weight"] = _t(np.asarray(mlp["router"]["kernel"]))
        for e in range(cfg.num_experts):
            sd[f"{ep}experts.{e}.w1.weight"] = _t(np.asarray(mlp["w_gate"][e]))
            sd[f"{ep}experts.{e}.w3.weight"] = _t(np.asarray(mlp["w_up"][e]))
            sd[f"{ep}experts.{e}.w2.weight"] = _t(np.asarray(mlp["w_down"][e]))
    return sd


def export_t5(backbone: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_t5`: T5Transformer param tree → HF
    T5ForConditionalGeneration state dict (the seq2seq leg of the
    reference's save path, ``trlx/models/modeling_ppo.py:1036-1113`` +
    ``accelerate_base_trainer.py:256-272``)."""
    gated = cfg.activation == "gated-gelu"
    shared = np.asarray(backbone["wte"]["embedding"])
    sd: Dict[str, np.ndarray] = {
        "shared.weight": shared,
        # tied aliases transformers includes in its own state dicts
        "encoder.embed_tokens.weight": shared,
        "decoder.embed_tokens.weight": shared,
        "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight": np.asarray(
            backbone["enc_rel_bias"]["rel_bias"]["embedding"]
        ),
        "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight": np.asarray(
            backbone["dec_rel_bias"]["rel_bias"]["embedding"]
        ),
        "encoder.final_layer_norm.weight": np.asarray(backbone["enc_ln_f"]["scale"]),
        "decoder.final_layer_norm.weight": np.asarray(backbone["dec_ln_f"]["scale"]),
    }

    def put_attn(prefix: str, attn: Dict[str, Any]) -> None:
        for ours, theirs in (
            ("q_proj", "q"), ("k_proj", "k"), ("v_proj", "v"), ("o_proj", "o"),
        ):
            sd[f"{prefix}.{theirs}.weight"] = _t(np.asarray(attn[ours]["kernel"]))

    def put_mlp(prefix: str, mlp: Dict[str, Any]) -> None:
        if gated:
            sd[f"{prefix}.wi_0.weight"] = _t(np.asarray(mlp["gate_proj"]["kernel"]))
            sd[f"{prefix}.wi_1.weight"] = _t(np.asarray(mlp["up_proj"]["kernel"]))
        else:
            sd[f"{prefix}.wi.weight"] = _t(np.asarray(mlp["up_proj"]["kernel"]))
        sd[f"{prefix}.wo.weight"] = _t(np.asarray(mlp["down_proj"]["kernel"]))

    for i in range(cfg.num_layers):
        lp = f"encoder.block.{i}."
        h = backbone[f"enc_{i}"]
        sd[lp + "layer.0.layer_norm.weight"] = np.asarray(h["ln_self"]["scale"])
        put_attn(lp + "layer.0.SelfAttention", h["self_attn"])
        sd[lp + "layer.1.layer_norm.weight"] = np.asarray(h["ln_mlp"]["scale"])
        put_mlp(lp + "layer.1.DenseReluDense", h["mlp"])
    for i in range(cfg.num_decoder_layers):
        lp = f"decoder.block.{i}."
        h = backbone[f"dec_{i}"]
        sd[lp + "layer.0.layer_norm.weight"] = np.asarray(h["ln_self"]["scale"])
        put_attn(lp + "layer.0.SelfAttention", h["self_attn"])
        sd[lp + "layer.1.layer_norm.weight"] = np.asarray(h["ln_cross"]["scale"])
        put_attn(lp + "layer.1.EncDecAttention", h["cross_attn"])
        sd[lp + "layer.2.layer_norm.weight"] = np.asarray(h["ln_mlp"]["scale"])
        put_mlp(lp + "layer.2.DenseReluDense", h["mlp"])
    sd["lm_head.weight"] = (
        shared if cfg.tie_word_embeddings
        else _t(np.asarray(backbone["lm_head"]["kernel"]))
    )
    return sd


EXPORTERS: Dict[str, Callable] = {
    "gpt2": export_gpt2,
    "llama": export_llama,
    "gpt_neox": export_gptneox,
    "gptj": export_gptj,
    "opt": export_opt,
    "bloom": export_bloom,
    "t5": export_t5,
    "mistral": export_llama,  # identical key layout
    "mixtral": export_mixtral,
}


def _export_mlp_head(sd: Dict[str, np.ndarray], prefix: str, head: Dict[str, Any]) -> None:
    """MLPHead → reference ``make_head`` Sequential(Linear, ReLU, Linear)
    torch names: ``{prefix}.0.*`` / ``{prefix}.2.*``."""
    _put_linear(sd, f"{prefix}.0", head["in_proj"])
    _put_linear(sd, f"{prefix}.2", head["out_proj"])


def merge_heads_into_state_dict(sd: Dict[str, np.ndarray], params: Dict[str, Any]) -> None:
    """Fold value/ILQL head params into ``sd`` under the reference's key
    names (``modeling_ppo.py:306-328``, ``modeling_ilql.py:322-344``)."""
    if "v_head" in params:
        _export_mlp_head(sd, "v_head", params["v_head"])
    if "ilql_heads" in params:
        heads = params["ilql_heads"]
        _export_mlp_head(sd, "ilql_heads.heads.v_head", heads["v_head"])
        for name, tree in sorted(heads.items()):
            if name.startswith("q_head_"):
                i = int(name[len("q_head_") :])
                _export_mlp_head(sd, f"ilql_heads.heads.q_heads.{i}", tree)
            elif name.startswith("target_q_head_"):
                i = int(name[len("target_q_head_") :])
                _export_mlp_head(sd, f"ilql_heads.heads.target_q_heads.{i}", tree)


def hf_config_from_transformer(cfg):
    """Inverse of :func:`config_from_hf`: TransformerConfig → transformers
    config object for the family in ``cfg.model_type``."""
    import transformers as tf

    mt = cfg.model_type
    if mt == "t5":
        return tf.T5Config(
            vocab_size=cfg.vocab_size,
            d_model=cfg.hidden_size,
            d_kv=cfg.head_dim,
            d_ff=cfg.intermediate_size,
            num_layers=cfg.num_layers,
            num_decoder_layers=cfg.num_decoder_layers,
            num_heads=cfg.num_heads,
            relative_attention_num_buckets=cfg.relative_attention_num_buckets,
            relative_attention_max_distance=cfg.relative_attention_max_distance,
            layer_norm_epsilon=cfg.layer_norm_epsilon,
            feed_forward_proj=cfg.activation,
            tie_word_embeddings=cfg.tie_word_embeddings,
            decoder_start_token_id=cfg.decoder_start_token_id,
            pad_token_id=cfg.pad_token_id,
        )
    if mt == "gpt2":
        return tf.GPT2Config(
            vocab_size=cfg.vocab_size,
            n_positions=cfg.max_position_embeddings,
            n_embd=cfg.hidden_size,
            n_layer=cfg.num_layers,
            n_head=cfg.num_heads,
            n_inner=cfg.intermediate_size,
            layer_norm_epsilon=cfg.layer_norm_epsilon,
        )
    if mt in ("llama", "mistral"):
        shared = dict(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            num_key_value_heads=cfg.kv_heads,
            intermediate_size=cfg.intermediate_size,
            max_position_embeddings=cfg.max_position_embeddings,
            rms_norm_eps=cfg.layer_norm_epsilon,
            rope_theta=cfg.rope_theta,
            tie_word_embeddings=cfg.tie_word_embeddings,
        )
        if mt == "llama":
            return tf.LlamaConfig(**shared)
        return tf.MistralConfig(
            head_dim=cfg.dims_per_head,
            sliding_window=cfg.sliding_window,
            **shared,
        )
    if mt == "mixtral":
        return tf.MixtralConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            num_key_value_heads=cfg.kv_heads,
            intermediate_size=cfg.intermediate_size,
            max_position_embeddings=cfg.max_position_embeddings,
            rms_norm_eps=cfg.layer_norm_epsilon,
            rope_theta=cfg.rope_theta,
            num_local_experts=cfg.num_experts,
            num_experts_per_tok=cfg.num_experts_per_tok,
            router_aux_loss_coef=cfg.router_aux_coef,
            sliding_window=cfg.sliding_window,
            tie_word_embeddings=cfg.tie_word_embeddings,
        )
    if mt == "gpt_neox":
        return tf.GPTNeoXConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            intermediate_size=cfg.intermediate_size,
            max_position_embeddings=cfg.max_position_embeddings,
            rotary_pct=(cfg.rotary_dim or cfg.dims_per_head) / cfg.dims_per_head,
            rotary_emb_base=cfg.rope_theta,
            use_parallel_residual=cfg.parallel_residual,
            layer_norm_eps=cfg.layer_norm_epsilon,
            tie_word_embeddings=False,
        )
    if mt == "gptj":
        return tf.GPTJConfig(
            vocab_size=cfg.vocab_size,
            n_positions=cfg.max_position_embeddings,
            n_embd=cfg.hidden_size,
            n_layer=cfg.num_layers,
            n_head=cfg.num_heads,
            n_inner=cfg.intermediate_size,
            rotary_dim=cfg.rotary_dim,
            layer_norm_epsilon=cfg.layer_norm_epsilon,
            tie_word_embeddings=False,
        )
    if mt == "opt":
        return tf.OPTConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            num_hidden_layers=cfg.num_layers,
            num_attention_heads=cfg.num_heads,
            ffn_dim=cfg.intermediate_size,
            max_position_embeddings=cfg.max_position_embeddings,
            activation_function=cfg.activation,
            word_embed_proj_dim=cfg.hidden_size,
            do_layer_norm_before=True,
        )
    if mt == "bloom":
        return tf.BloomConfig(
            vocab_size=cfg.vocab_size,
            hidden_size=cfg.hidden_size,
            n_layer=cfg.num_layers,
            n_head=cfg.num_heads,
            layer_norm_epsilon=cfg.layer_norm_epsilon,
        )
    raise UnsupportedHFExport(
        f"No HF export mapping for model_type={mt!r} "
        "(set TransformerConfig.model_type to an HF family)"
    )


def params_to_hf_state_dict(params: Dict[str, Any], cfg) -> Dict[str, np.ndarray]:
    """Full param tree (backbone + any heads) → HF torch-layout state dict.

    Handles the scan_layers stacked layout and folds trained LoRA adapters
    into their base kernels (reference exports merged weights too — OpenDelta
    merges on save).
    """
    from trlx_tpu.models.builder import merge_lora_params
    from trlx_tpu.models.transformer import unstack_layer_params

    if cfg.model_type not in EXPORTERS:
        raise UnsupportedHFExport(
            f"No HF exporter for model_type={cfg.model_type!r}; known: {sorted(EXPORTERS)}"
        )
    backbone = params.get("backbone", params)
    backbone = unstack_layer_params(backbone)
    backbone = merge_lora_params(backbone, cfg)
    sd = EXPORTERS[cfg.model_type](backbone, cfg)
    if "backbone" in params:
        merge_heads_into_state_dict(sd, params)
    return sd


def save_pretrained_hf(
    directory: str,
    params: Dict[str, Any],
    cfg,
    tokenizer_path: Optional[str] = None,
) -> None:
    """Write a transformers-loadable checkpoint directory:
    ``pytorch_model.bin`` (fp32 torch tensors, heads merged under their
    reference prefixes) + ``config.json``; tokenizer files are copied when
    ``tokenizer_path`` is a local directory. The reference's
    ``save_pretrained`` contract (``accelerate_base_trainer.py:256-272``)."""
    import os
    import shutil

    import torch

    os.makedirs(directory, exist_ok=True)
    sd = params_to_hf_state_dict(params, cfg)
    tensors = {
        k: torch.tensor(np.asarray(v, dtype=np.float32)) for k, v in sd.items()
    }
    torch.save(tensors, os.path.join(directory, "pytorch_model.bin"))
    hf_config_from_transformer(cfg).save_pretrained(directory)
    if tokenizer_path and os.path.isdir(tokenizer_path):
        for name in os.listdir(tokenizer_path):
            if "token" in name or name in ("vocab.json", "merges.txt", "special_tokens_map.json"):
                shutil.copy(os.path.join(tokenizer_path, name), directory)
