"""TPU-native T5-family encoder-decoder backbone (Flax linen).

The reference's seq2seq path wraps HF T5 for PPO and ILQL
(``trlx/models/modeling_ppo.py:948-1222``, ``modeling_ilql.py:347-488``; used
by ``examples/ppo_sentiments_t5.py``). Here the same capability is a single
configurable encoder-decoder covering T5 v1.0 (relu FFN, tied embeddings:
t5-small/base/large/3b/11b) and v1.1/Flan (gated-GELU, untied: flan-t5-*),
built on the same conventions as ``CausalTransformer``:

- identical parameter naming (``q_proj``/``o_proj``/``up_proj``/``wte``/…) so
  the one sharding rule table (``trlx_tpu/parallel/sharding.py``) maps the
  whole model onto the ``(data, pipe, fsdp, model, sequence)`` mesh;
- explicit functional KV cache for the decoder (self-attn K/V written at
  ``cache_index``; cross-attn K/V computed once at prefill), so seq2seq
  generation is one compiled ``lax.while_loop`` program;
- a ``forward_branch`` that replays the top-k *decoder* blocks on trunk
  activations — the hydra frozen-reference trick for seq2seq PPO (reference
  ``T5Branch``, ``modeling_ppo.py:1113-1222``). The parametric relative
  position bias is computed once by the shared frozen trunk and threaded into
  the branch, matching the semantics of bottom-layers-frozen training.

T5 numerics notes (matched to the public architecture): RMS layernorm without
mean subtraction, **no** 1/sqrt(d) attention scaling, relative position bias
added in layer 0 and shared across layers, and a d_model**-0.5 logit scaling
when embeddings are tied.
"""

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.models.transformer import param_with_axes


@dataclasses.dataclass(frozen=True)
class Seq2SeqConfig:
    """Architecture description of a T5-style encoder-decoder."""

    vocab_size: int
    hidden_size: int  # d_model
    num_layers: int  # encoder layers
    num_decoder_layers: int
    num_heads: int
    head_dim: int  # d_kv (not necessarily hidden/heads for t5-small!)
    intermediate_size: int  # d_ff

    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    activation: str = "relu"  # relu (v1.0) | gated-gelu (v1.1 / flan)
    tie_word_embeddings: bool = True
    decoder_start_token_id: int = 0
    pad_token_id: int = 0

    param_dtype: Any = jnp.float32
    dtype: Any = jnp.bfloat16
    remat: str = "none"

    # LoRA (see TransformerConfig.lora_*); r=0 disables
    lora_r: int = 0
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ()

    # duck-type compatibility with TransformerConfig consumers (heads, ILQL)
    @property
    def kv_heads(self) -> int:
        return self.num_heads

    @property
    def dims_per_head(self) -> int:
        return self.head_dim

    @property
    def is_seq2seq(self) -> bool:
        return True

    @property
    def model_type(self) -> str:
        # HF family tag — enables the checkpoint layer's HF-format export
        # (EXPORTERS["t5"]) exactly like the causal families
        return "t5"

    @staticmethod
    def t5(size: str = "small", **overrides) -> "Seq2SeqConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_decoder_layers=2, num_heads=4, head_dim=16, intermediate_size=128, relative_attention_num_buckets=8, relative_attention_max_distance=20),
            "small": dict(vocab_size=32128, hidden_size=512, num_layers=6, num_decoder_layers=6, num_heads=8, head_dim=64, intermediate_size=2048),
            "base": dict(vocab_size=32128, hidden_size=768, num_layers=12, num_decoder_layers=12, num_heads=12, head_dim=64, intermediate_size=3072),
            "large": dict(vocab_size=32128, hidden_size=1024, num_layers=24, num_decoder_layers=24, num_heads=16, head_dim=64, intermediate_size=4096),
            "3b": dict(vocab_size=32128, hidden_size=1024, num_layers=24, num_decoder_layers=24, num_heads=32, head_dim=128, intermediate_size=16384),
            "11b": dict(vocab_size=32128, hidden_size=1024, num_layers=24, num_decoder_layers=24, num_heads=128, head_dim=128, intermediate_size=65536),
        }[size]
        dims.update(overrides)
        return Seq2SeqConfig(activation="relu", tie_word_embeddings=True, **dims)

    @staticmethod
    def flan_t5(size: str = "small", **overrides) -> "Seq2SeqConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_decoder_layers=2, num_heads=4, head_dim=16, intermediate_size=128, relative_attention_num_buckets=8, relative_attention_max_distance=20),
            "small": dict(vocab_size=32128, hidden_size=512, num_layers=8, num_decoder_layers=8, num_heads=6, head_dim=64, intermediate_size=1024),
            "base": dict(vocab_size=32128, hidden_size=768, num_layers=12, num_decoder_layers=12, num_heads=12, head_dim=64, intermediate_size=2048),
            "large": dict(vocab_size=32128, hidden_size=1024, num_layers=24, num_decoder_layers=24, num_heads=16, head_dim=64, intermediate_size=2816),
            "xl": dict(vocab_size=32128, hidden_size=2048, num_layers=24, num_decoder_layers=24, num_heads=32, head_dim=64, intermediate_size=5120),
            "xxl": dict(vocab_size=32128, hidden_size=4096, num_layers=24, num_decoder_layers=24, num_heads=64, head_dim=64, intermediate_size=10240),
        }[size]
        dims.update(overrides)
        return Seq2SeqConfig(activation="gated-gelu", tie_word_embeddings=False, **dims)


def _t5_dense(cfg, features, kernel_axes, name, lora_ok=True):
    kernel_init = param_with_axes(nn.initializers.normal(0.02), kernel_axes)
    if lora_ok and cfg.lora_r and name in cfg.lora_targets:
        from trlx_tpu.models.transformer import LoRADense

        return LoRADense(
            features, False, cfg.dtype, cfg.param_dtype, kernel_init,
            nn.initializers.zeros, cfg.lora_r, cfg.lora_alpha, name=name,
        )
    return nn.Dense(
        features,
        use_bias=False,  # T5 uses no biases anywhere
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=kernel_init,
        name=name,
    )


def _t5_norm(cfg, name):
    # T5 layer norm: RMS without mean subtraction, scale only
    return nn.RMSNorm(
        epsilon=cfg.layer_norm_epsilon,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        scale_init=param_with_axes(nn.initializers.ones, ("embed",)),
        name=name,
    )


def relative_position_bucket(
    relative_position: jax.Array,  # k_pos - q_pos
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """T5's log-bucketed relative position (public T5 bucket scheme)."""
    ret = jnp.zeros_like(relative_position)
    n = relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n > 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(-n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class RelativePositionBias(nn.Module):
    """The parametric rel-pos bias table, owned by layer 0 of each stack."""

    config: Seq2SeqConfig
    bidirectional: bool

    @nn.compact
    def __call__(self, q_positions: jax.Array, k_positions: jax.Array) -> jax.Array:
        """[Tq], [Tk] → additive bias [1, H, Tq, Tk]."""
        cfg = self.config
        rel = k_positions[None, :] - q_positions[:, None]  # [Tq, Tk]
        buckets = relative_position_bucket(
            rel,
            self.bidirectional,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance,
        )
        table = nn.Embed(
            cfg.relative_attention_num_buckets,
            cfg.num_heads,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            embedding_init=param_with_axes(nn.initializers.normal(0.02), ("rel_buckets", "heads")),
            name="rel_bias",
        )(buckets)  # [Tq, Tk, H]
        return table.transpose(2, 0, 1)[None]  # [1, H, Tq, Tk]


class T5Attention(nn.Module):
    """Self- or cross-attention, T5 style (no 1/sqrt(d) scaling, no biases)."""

    config: Seq2SeqConfig
    # encoder modules skip LoRA: the reference restricts adapters to decoder
    # blocks for T5 (``trlx/utils/modeling.py:400-402``), so encoder adapters
    # could never train and would be dead weight
    lora_ok: bool = True

    def setup(self):
        cfg = self.config
        HD = cfg.num_heads * cfg.head_dim
        ok = self.lora_ok
        self.q_proj = _t5_dense(cfg, HD, ("embed", "joined_kv"), "q_proj", ok)
        self.k_proj = _t5_dense(cfg, HD, ("embed", "joined_kv"), "k_proj", ok)
        self.v_proj = _t5_dense(cfg, HD, ("embed", "joined_kv"), "v_proj", ok)
        self.o_proj = _t5_dense(cfg, cfg.hidden_size, ("joined_kv", "embed"), "o_proj", ok)

    def __call__(
        self,
        x: jax.Array,  # [B, T, E] queries
        kv: Optional[jax.Array] = None,  # [B, S, E] for cross-attn (None: self)
        bias: Optional[jax.Array] = None,  # [B or 1, H, T, S] additive
        cache: Optional[Dict[str, jax.Array]] = None,
        cache_index: Optional[jax.Array] = None,
        precomputed_kv: Optional[Tuple[jax.Array, jax.Array]] = None,
    ):
        cfg = self.config
        B, T, _ = x.shape
        H, D = cfg.num_heads, cfg.head_dim

        q = self.q_proj(x).reshape(B, T, H, D)
        if precomputed_kv is not None:
            k, v = precomputed_kv  # cross-attn during decode
        else:
            src = x if kv is None else kv
            S = src.shape[1]
            k = self.k_proj(src).reshape(B, S, H, D)
            v = self.v_proj(src).reshape(B, S, H, D)

        new_cache = None
        if cache is not None:  # decoder self-attn: write this step into cache
            k_cache = jax.lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype), (0, cache_index, 0, 0)
            )
            v_cache = jax.lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype), (0, cache_index, 0, 0)
            )
            k, v = k_cache, v_cache
            new_cache = {"k": k_cache, "v": v_cache}

        scores = jnp.einsum("bthd,bshd->bhts", q, k)  # NOTE: no sqrt(d) scale
        if bias is not None:
            scores = scores + bias.astype(scores.dtype)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, H * D)
        out = self.o_proj(out)
        return out, new_cache

    def compute_kv(self, src: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Project cross-attention K/V once (decode-time prefill)."""
        cfg = self.config
        B, S, _ = src.shape
        H, D = cfg.num_heads, cfg.head_dim
        return (
            self.k_proj(src).reshape(B, S, H, D),
            self.v_proj(src).reshape(B, S, H, D),
        )


class T5MLP(nn.Module):
    config: Seq2SeqConfig
    lora_ok: bool = True

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ok = self.lora_ok
        if cfg.activation == "gated-gelu":
            gate = _t5_dense(cfg, cfg.intermediate_size, ("embed", "ffn"), "gate_proj", ok)(x)
            up = _t5_dense(cfg, cfg.intermediate_size, ("embed", "ffn"), "up_proj", ok)(x)
            h = nn.gelu(gate, approximate=True) * up
        else:
            h = nn.relu(_t5_dense(cfg, cfg.intermediate_size, ("embed", "ffn"), "up_proj", ok)(x))
        return _t5_dense(cfg, cfg.hidden_size, ("ffn", "embed"), "down_proj", ok)(h)


class T5EncoderBlock(nn.Module):
    config: Seq2SeqConfig

    def setup(self):
        cfg = self.config
        self.ln_self = _t5_norm(cfg, "ln_self")
        self.self_attn = T5Attention(cfg, lora_ok=False, name="self_attn")
        self.ln_mlp = _t5_norm(cfg, "ln_mlp")
        self.mlp = T5MLP(cfg, lora_ok=False, name="mlp")

    def __call__(self, x, bias):
        h, _ = self.self_attn(self.ln_self(x), bias=bias)
        x = x + h
        x = x + self.mlp(self.ln_mlp(x))
        return x


class T5DecoderBlock(nn.Module):
    config: Seq2SeqConfig

    def setup(self):
        cfg = self.config
        self.ln_self = _t5_norm(cfg, "ln_self")
        self.self_attn = T5Attention(cfg, name="self_attn")
        self.ln_cross = _t5_norm(cfg, "ln_cross")
        self.cross_attn = T5Attention(cfg, name="cross_attn")
        self.ln_mlp = _t5_norm(cfg, "ln_mlp")
        self.mlp = T5MLP(cfg, name="mlp")

    def __call__(
        self,
        x,
        self_bias,
        enc_hidden,
        cross_bias,
        cache=None,
        cache_index=None,
        cross_kv=None,
    ):
        h, new_cache = self.self_attn(
            self.ln_self(x), bias=self_bias, cache=cache, cache_index=cache_index
        )
        x = x + h
        h, _ = self.cross_attn(
            self.ln_cross(x), kv=enc_hidden, bias=cross_bias, precomputed_kv=cross_kv
        )
        x = x + h
        x = x + self.mlp(self.ln_mlp(x))
        return x, new_cache

    def cross_kv(self, enc_hidden: jax.Array) -> Tuple[jax.Array, jax.Array]:
        return self.cross_attn.compute_kv(enc_hidden)


class T5Transformer(nn.Module):
    """Full encoder-decoder. Decoder slots are positions (no left-padding)."""

    config: Seq2SeqConfig

    def setup(self):
        cfg = self.config
        self.wte = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=param_with_axes(nn.initializers.normal(1.0), ("vocab", "embed")),
            name="wte",
        )
        enc_block = T5EncoderBlock
        dec_block = T5DecoderBlock
        if cfg.remat == "full":
            enc_block = nn.remat(T5EncoderBlock)
            dec_block = nn.remat(T5DecoderBlock, methods=["__call__", "cross_kv"])
        self.enc_rel_bias = RelativePositionBias(cfg, bidirectional=True, name="enc_rel_bias")
        self.dec_rel_bias = RelativePositionBias(cfg, bidirectional=False, name="dec_rel_bias")
        self.enc_blocks = [enc_block(cfg, name=f"enc_{i}") for i in range(cfg.num_layers)]
        self.dec_blocks = [dec_block(cfg, name=f"dec_{i}") for i in range(cfg.num_decoder_layers)]
        self.enc_ln_f = _t5_norm(cfg, "enc_ln_f")
        self.dec_ln_f = _t5_norm(cfg, "dec_ln_f")
        if not cfg.tie_word_embeddings:
            self.lm_head = _t5_dense(cfg, cfg.vocab_size, ("embed", "vocab"), "lm_head")

    # ---- pieces ----

    def _logits(self, h):
        cfg = self.config
        if cfg.tie_word_embeddings:
            return self.wte.attend(h * (cfg.hidden_size ** -0.5))
        return self.lm_head(h)

    def _pad_bias(self, mask: jax.Array, Tq: int) -> jax.Array:
        """[B, S] key mask → additive [B, 1, Tq, S]."""
        neg = jnp.asarray(-1e9, jnp.float32)
        return jnp.where(mask[:, None, None, :] > 0, 0.0, neg) * jnp.ones(
            (1, 1, Tq, 1), jnp.float32
        )

    def encode(self, input_ids: jax.Array, attention_mask: Optional[jax.Array] = None) -> jax.Array:
        B, S = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), jnp.int32)
        pos = jnp.arange(S)
        bias = self.enc_rel_bias(pos, pos) + self._pad_bias(attention_mask, S)
        x = self.wte(input_ids)
        for block in self.enc_blocks:
            x = block(x, bias)
        return self.enc_ln_f(x)

    def decode(
        self,
        decoder_input_ids: jax.Array,  # [B, T]
        encoder_hidden: jax.Array,  # [B, S, E]
        encoder_mask: jax.Array,  # [B, S]
        decoder_mask: Optional[jax.Array] = None,  # [B, T] (right-padded)
        cache: Optional[List[Dict[str, Any]]] = None,
        cache_index: Optional[jax.Array] = None,
        branch_layer: Optional[int] = None,
        logits_span: Optional[Tuple[int, int]] = None,  # static [a, b) span of
        # decoder positions to project ((0, 0) = hidden states only)
    ) -> Dict[str, Any]:
        cfg = self.config
        B, T = decoder_input_ids.shape
        x = self.wte(decoder_input_ids)

        if cache is None:
            q_pos = jnp.arange(T)
            k_pos = jnp.arange(T)
            self_bias = self.dec_rel_bias(q_pos, k_pos)
            self_bias = self_bias + jnp.where(
                (k_pos[None, :] <= q_pos[:, None])[None, None], 0.0, -1e9
            )
            if decoder_mask is not None:
                self_bias = self_bias + self._pad_bias(decoder_mask, T)
        else:
            S_dec = cache[0]["k"].shape[1]
            q_pos = cache_index + jnp.arange(T)
            k_pos = jnp.arange(S_dec)
            self_bias = self.dec_rel_bias(q_pos, k_pos)
            self_bias = self_bias + jnp.where(
                (k_pos[None, :] <= q_pos[:, None])[None, None], 0.0, -1e9
            )
        cross_bias = self._pad_bias(encoder_mask, T)

        branch_input = None
        new_cache = [] if cache is not None else None
        for i, block in enumerate(self.dec_blocks):
            if branch_layer is not None and i == len(self.dec_blocks) - branch_layer:
                branch_input = x
            layer_cache = cache[i] if cache is not None else None
            cross_kv = (
                (layer_cache["ck"], layer_cache["cv"]) if layer_cache is not None else None
            )
            x, updated = block(
                x, self_bias, encoder_hidden, cross_bias,
                cache=layer_cache, cache_index=cache_index, cross_kv=cross_kv,
            )
            if cache is not None:
                updated["ck"], updated["cv"] = layer_cache["ck"], layer_cache["cv"]
                new_cache.append(updated)

        h = self.dec_ln_f(x)
        return {
            "logits": self._logits(
                h if logits_span is None else h[:, logits_span[0] : logits_span[1]]
            ),
            "hidden_states": h,
            "pre_norm_hidden": x,
            "branch_input": branch_input,
            "cache": new_cache,
        }

    def project_logits(self, hidden: jax.Array) -> jax.Array:
        """Vocab projection of (already final-normed) decoder hidden states —
        lets losses project gathered/chunked positions instead of the full
        ``[B, T, V]`` tensor (mirrors ``CausalTransformer.project_logits``)."""
        return self._logits(hidden)

    def __call__(
        self,
        input_ids: jax.Array,  # encoder tokens [B, S]
        attention_mask: Optional[jax.Array] = None,  # [B, S]
        decoder_input_ids: Optional[jax.Array] = None,  # [B, T]
        decoder_attention_mask: Optional[jax.Array] = None,
        branch_layer: Optional[int] = None,
        logits_span: Optional[Tuple[int, int]] = None,
    ) -> Dict[str, Any]:
        cfg = self.config
        B = input_ids.shape[0]
        if attention_mask is None:
            attention_mask = jnp.ones(input_ids.shape, jnp.int32)
        if decoder_input_ids is None:
            decoder_input_ids = jnp.full((B, 1), cfg.decoder_start_token_id, jnp.int32)
        enc = self.encode(input_ids, attention_mask)
        out = self.decode(
            decoder_input_ids, enc, attention_mask,
            decoder_mask=decoder_attention_mask, branch_layer=branch_layer,
            logits_span=logits_span,
        )
        out["encoder_hidden"] = enc
        return out

    def forward_branch(
        self,
        hidden_states: jax.Array,  # [B, T, E] decoder activations entering branch
        branch_layer: int,
        encoder_hidden: jax.Array,
        encoder_mask: jax.Array,
        decoder_mask: Optional[jax.Array] = None,
    ) -> Dict[str, Any]:
        """Replay the top ``branch_layer`` decoder blocks + final norm + head
        (seq2seq hydra reference branch, reference ``T5Branch``
        ``modeling_ppo.py:1113-1222``). The rel-pos bias is recomputed from
        this (frozen) branch's own table — identical to the policy's because
        layer 0 of the decoder is part of the frozen trunk."""
        B, T, _ = hidden_states.shape
        q_pos = jnp.arange(T)
        self_bias = self.dec_rel_bias(q_pos, q_pos)
        self_bias = self_bias + jnp.where(
            (q_pos[None, :] <= q_pos[:, None])[None, None], 0.0, -1e9
        )
        if decoder_mask is not None:
            self_bias = self_bias + self._pad_bias(decoder_mask, T)
        cross_bias = self._pad_bias(encoder_mask, T)
        x = hidden_states
        for block in self.dec_blocks[len(self.dec_blocks) - branch_layer :]:
            x, _ = block(x, self_bias, encoder_hidden, cross_bias)
        h = self.dec_ln_f(x)
        return {"logits": self._logits(h), "hidden_states": h}

    def encode_for_decode(
        self, input_ids: jax.Array, attention_mask: jax.Array, max_decode_len: int
    ) -> Tuple[jax.Array, List[Dict[str, jax.Array]]]:
        """Encoder pass + fresh decoder cache with cross-attn K/V prefilled
        (computed once per sequence, reused by every decode step)."""
        cfg = self.config
        B = input_ids.shape[0]
        enc = self.encode(input_ids, attention_mask)
        cache = []
        for i in range(cfg.num_decoder_layers):
            ck, cv = self.dec_blocks[i].cross_kv(enc)
            cache.append(
                {
                    "k": jnp.zeros((B, max_decode_len, cfg.num_heads, cfg.head_dim), cfg.dtype),
                    "v": jnp.zeros((B, max_decode_len, cfg.num_heads, cfg.head_dim), cfg.dtype),
                    "ck": ck,
                    "cv": cv,
                }
            )
        return enc, cache
