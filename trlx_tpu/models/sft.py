"""SFT method config + loss.

Reference: ``SFTConfig`` and the cross-entropy loss with -100 label masking in
``trlx/trainer/accelerate_sft_trainer.py:16-75``.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.method_configs import MethodConfig, register_method

IGNORE_INDEX = -100


def _token_nll(logits: jax.Array, labels: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-token NLL with IGNORE_INDEX masking — the single definition of
    the CE body, shared by the full and chunked loss paths."""
    mask = (labels != IGNORE_INDEX).astype(jnp.float32)
    safe = jnp.where(labels == IGNORE_INDEX, 0, labels)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return nll, mask


@dataclass
@register_method("SFTConfig")
class SFTConfig(MethodConfig):
    """Supervised fine-tuning: plain next-token CE, optionally loss-masked to
    output segments of a dialogue (labels == -100 are ignored)."""

    name: str = "SFTConfig"
    gen_kwargs: Dict[str, Any] = field(default_factory=dict)
    # stream the vocab projection + CE in T-chunks of this size instead of
    # materializing [B, T, V] logits (0 = off). At BLOOM's 250k vocab the
    # logits tensor dominates peak training memory; chunking bounds it at
    # [B, logit_chunk, V] (backward rematerializes per chunk).
    logit_chunk: int = 0

    def loss(
        self,
        logits: jax.Array,  # [B, T, V]
        labels: jax.Array,  # [B, T]; IGNORE_INDEX positions excluded
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        # standard causal shift: logits at t predict labels at t+1
        token_nll, mask = _token_nll(logits[:, :-1], labels[:, 1:])
        n = jnp.maximum(mask.sum(), 1.0)
        loss = jnp.sum(token_nll * mask) / n
        return loss, {"losses/loss": loss, "losses/ppl": jnp.exp(loss)}

    def chunked_loss(
        self,
        module,
        params,
        hidden: jax.Array,  # [B, T, E] final-normed hidden states
        labels: jax.Array,  # [B, T]
        chunk: int,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Numerically identical to :meth:`loss`, but the full ``[B, T, V]``
        logits are never materialized: hidden chunks stream through the
        model's ``project_logits`` under ``jax.checkpoint`` (forward AND
        backward peak at ``[B, chunk, V]``)."""
        from trlx_tpu.ops.chunked import stream_projected_reduce

        def body(carry, logits, l):
            nll, m = _token_nll(logits, l)
            s, n = carry
            return s + jnp.sum(nll * m), n + jnp.sum(m)

        s, n = stream_projected_reduce(
            module,
            params,
            hidden[:, :-1],
            [(labels[:, 1:], IGNORE_INDEX)],
            chunk,
            (jnp.asarray(0.0, jnp.float32), jnp.asarray(0.0, jnp.float32)),
            body,
        )
        loss = s / jnp.maximum(n, 1.0)
        return loss, {"losses/loss": loss, "losses/ppl": jnp.exp(loss)}
