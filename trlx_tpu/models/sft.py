"""SFT method config + loss.

Reference: ``SFTConfig`` and the cross-entropy loss with -100 label masking in
``trlx/trainer/accelerate_sft_trainer.py:16-75``.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.method_configs import MethodConfig, register_method

IGNORE_INDEX = -100


@dataclass
@register_method("SFTConfig")
class SFTConfig(MethodConfig):
    """Supervised fine-tuning: plain next-token CE, optionally loss-masked to
    output segments of a dialogue (labels == -100 are ignored)."""

    name: str = "SFTConfig"
    gen_kwargs: Dict[str, Any] = field(default_factory=dict)

    def loss(
        self,
        logits: jax.Array,  # [B, T, V]
        labels: jax.Array,  # [B, T]; IGNORE_INDEX positions excluded
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        # standard causal shift: logits at t predict labels at t+1
        shift_logits = logits[:, :-1].astype(jnp.float32)
        shift_labels = labels[:, 1:]
        mask = (shift_labels != IGNORE_INDEX).astype(jnp.float32)
        safe_labels = jnp.where(shift_labels == IGNORE_INDEX, 0, shift_labels)
        logp = jax.nn.log_softmax(shift_logits, axis=-1)
        token_nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        n = jnp.maximum(mask.sum(), 1.0)
        loss = jnp.sum(token_nll * mask) / n
        return loss, {"losses/loss": loss, "losses/ppl": jnp.exp(loss)}
