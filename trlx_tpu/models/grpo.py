"""GRPO method: group-relative advantages + clipped objective, no value head.

Beyond the reference (trlx v0.6.0 ships PPO/ILQL/SFT only): Group Relative
Policy Optimization (Shao et al. 2024, DeepSeekMath §4.1) samples a *group*
of responses per prompt and uses the group-normalized reward as a per-sequence
advantage, dropping the value function entirely — half the trainable state
and no GAE/value-loss machinery. The KL penalty moves from reward shaping
into the loss (the unbiased k3 estimator against the frozen reference).

Plugs into the same registries the reference's methods use
(``trlx/data/method_configs.py:9-56``): ``GRPOConfig`` subclasses
:class:`~trlx_tpu.models.ppo.PPOConfig`, so the PPO trainer's rollout
machinery (jitted generation, hydra reference branch, score-free overlap)
is inherited wholesale by :class:`~trlx_tpu.trainer.grpo.GRPOTrainer`.
"""

from dataclasses import dataclass
from typing import Any, ClassVar, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.method_configs import register_method
from trlx_tpu.models.ppo import PPOConfig
from trlx_tpu.utils import flatten_dict
from trlx_tpu.utils.stats import get_tensor_stats


BASELINES = ("group", "rloo")  # the one whitelist (trainer validation imports it)


def group_advantages_np(
    scores: np.ndarray,
    group_size: int,
    scale: bool = True,
    eps: float = 1e-6,
    baseline: str = "group",
) -> np.ndarray:
    """Per-sequence advantages from grouped rewards (host side, numpy).

    ``scores`` [B] must be laid out group-contiguously (the rollout loop
    repeats each prompt ``group_size`` times in a row). ``scale=False``
    skips the per-group std division (the "Dr. GRPO" variant, which removes
    the difficulty bias of std normalization).

    ``baseline="rloo"`` uses the leave-one-out mean of the OTHER group
    members as each sequence's baseline (REINFORCE-Leave-One-Out, Kool et
    al. 2019; Ahmadian et al. 2024) — an unbiased baseline, since a
    sequence's own reward never appears in it. Requires ``group_size >= 2``
    and ignores ``scale`` (RLOO is unscaled by definition).
    """
    if scores.shape[0] % group_size:
        raise ValueError(
            f"batch {scores.shape[0]} not divisible by group_size {group_size}"
        )
    g = scores.reshape(-1, group_size)
    if baseline == "rloo":
        if group_size < 2:
            raise ValueError("rloo baseline needs group_size >= 2")
        loo_mean = (g.sum(axis=1, keepdims=True) - g) / (group_size - 1)
        return (g - loo_mean).reshape(-1).astype(np.float32)
    if baseline != "group":
        raise ValueError(f"unknown baseline '{baseline}'; known: {BASELINES}")
    adv = g - g.mean(axis=1, keepdims=True)
    if scale:
        adv = adv / (g.std(axis=1, keepdims=True) + eps)
    return adv.reshape(-1).astype(np.float32)


@dataclass
@register_method("GRPOConfig")
class GRPOConfig(PPOConfig):
    """GRPO hyperparameters.

    Inherits the PPO sampling/rollout knobs; the value-function fields
    (``cliprange_value``, ``vf_coef``, ``gamma``, ``lam``) are unused.

    :param group_size: responses sampled per prompt; ``chunk_size`` must be
        a multiple of it.
    :param beta: coefficient of the in-loss KL penalty vs the frozen
        reference (k3 estimator) — replaces PPO's KL-shaped rewards.
    :param scale_advantage: divide group-centered rewards by the group std
        (True = original GRPO; False = Dr. GRPO).
    :param baseline: ``"group"`` (group-mean baseline, GRPO) or ``"rloo"``
        (leave-one-out mean — REINFORCE-Leave-One-Out; unbiased baseline,
        no std scaling).
    """

    #: GRPO's group-baseline loss has no GAE recurrence or value head, so the
    #: fused Pallas learner kernel (``ops/fused_loss.py``) has nothing to fuse
    #: here — narrow the hostable loss_kernel values back to the XLA path.
    LOSS_KERNELS: ClassVar[Tuple[str, ...]] = ("xla",)

    name: str = "GRPOConfig"
    group_size: int = 8
    beta: float = 0.04
    scale_advantage: bool = True
    baseline: str = "group"

    def loss(
        self,
        logprobs: jax.Array,  # [B, R] current policy logprobs of response tokens
        old_logprobs: jax.Array,  # [B, R] behavior logprobs at collection time
        ref_logprobs: jax.Array,  # [B, R] frozen-reference logprobs
        advantages: jax.Array,  # [B] per-sequence group-relative advantages
        mask: jax.Array,  # [B, R] response mask
        behavior_logprobs: jax.Array = None,  # [B, R] sampler logprobs (async)
    ) -> Tuple[jax.Array, Dict[str, Any]]:
        """Clipped ratio objective with sequence-level advantages and an
        in-loss KL penalty; token-mean normalization (masked).
        ``behavior_logprobs`` (async collection, ``iw_correction: clip``)
        applies the truncated proximal/behavior importance weight to the pg
        term — ``None`` keeps the serial objective byte-for-byte."""
        from trlx_tpu.models.ppo import iw_weights

        mask = mask.astype(jnp.float32)
        n = jnp.maximum(mask.sum(), 1.0)
        adv = advantages.astype(jnp.float32)[:, None]

        log_ratio = (logprobs - old_logprobs) * mask
        ratio = jnp.exp(log_ratio)
        pg_loss1 = -adv * ratio
        pg_loss2 = -adv * jnp.clip(ratio, 1.0 - self.cliprange, 1.0 + self.cliprange)
        iw_stats = {}
        if behavior_logprobs is not None and self.iw_correction != "off":
            rho, iw_stats = iw_weights(
                old_logprobs, behavior_logprobs, mask, self.iw_clip, n
            )
            pg_loss1 = pg_loss1 * rho
            pg_loss2 = pg_loss2 * rho
        pg_loss = jnp.sum(jnp.maximum(pg_loss1, pg_loss2) * mask) / n

        # k3 KL estimator vs the frozen reference (Schulman 2020): unbiased,
        # guaranteed non-negative — exp(δ) − δ − 1 with δ = ref − current
        delta = (ref_logprobs - logprobs) * mask
        kl = jnp.sum((jnp.exp(delta) - delta - 1.0) * mask) / n

        loss = pg_loss + self.beta * kl

        approx_kl_old = 0.5 * jnp.sum(log_ratio**2) / n  # vs behavior policy
        clipfrac = jnp.sum((pg_loss2 > pg_loss1).astype(jnp.float32) * mask) / n
        dist = {}
        if self.dist_sketches:
            from trlx_tpu.observability.dynamics import loss_sketches

            # per-token ref-KL is the k3 integrand GRPO already penalizes;
            # advantages are per-sequence [B] (mask=None — every row counts)
            dist = loss_sketches(
                {
                    "log_ratio": (log_ratio, mask),
                    "ref_kl": (jnp.exp(delta) - delta - 1.0, mask),
                    "advantages": (advantages, None),
                }
            )
        stats = dict(
            **iw_stats,
            **dist,
            losses=dict(
                total_loss=loss,
                policy_loss=pg_loss,
                kl_loss=kl,
            ),
            ratio=get_tensor_stats(ratio, mask, n),
            advantages_mean=jnp.mean(adv),
            policy=dict(approx_kl=approx_kl_old, clipfrac=clipfrac, ref_kl=kl),
            padding_percentage=1.0 - n / mask.size,
        )
        return loss, flatten_dict(stats)
