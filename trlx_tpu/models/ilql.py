"""ILQL method: config + loss (twin-Q TD, expectile V, CQL, AWAC) — pure JAX.

Behavioral parity target: ``ILQLConfig.loss`` (``trlx/models/modeling_ilql.py:60-132``)
and the helpers ``topk_mask:28`` / ``batched_index_select:35``. The heads
themselves live in ``trlx_tpu/models/heads.py``; the advantage-reshaped
sampler in ``trlx_tpu/ops/sampling.py``.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.ilql_types import ILQLBatch, ILQLSeq2SeqBatch  # noqa: F401
from trlx_tpu.data.method_configs import MethodConfig, register_method
from trlx_tpu.utils import flatten_dict
from trlx_tpu.utils.stats import get_tensor_stats


def topk_mask(xs: jax.Array, k: int) -> jax.Array:
    """Set all but the top-k entries of the last axis to -inf."""
    if k >= xs.shape[-1]:
        return xs
    mintop = jax.lax.top_k(xs, k)[0][..., -1:]
    return jnp.where(xs < mintop, -jnp.inf, xs)


def batched_index_select(x: jax.Array, idxs: jax.Array, axis: int = 1) -> jax.Array:
    """Gather rows at ``idxs`` along ``axis``: [B, T, H], [B, I] → [B, I, H]."""
    return jnp.take_along_axis(x, jnp.expand_dims(idxs, -1), axis=axis)


@dataclass
@register_method("ILQLConfig")
class ILQLConfig(MethodConfig):
    """ILQL hyperparameters (field-compatible with the reference's
    ``ILQLConfig``, ``trlx/models/modeling_ilql.py:47-57``).

    :param tau: expectile for the V loss
    :param gamma: discount
    :param cql_scale: weight of the conservative (CQL) regularizer
    :param awac_scale: weight of the AWAC-weighted CE term
    :param alpha: Polyak rate for target-Q sync
    :param beta: advantage scaling in the AWAC weight exp(β(Q−V))
    :param steps_for_target_q_sync: opt steps between target-Q Polyak syncs
    :param two_qs: use twin Q heads (min for targets)
    :param gen_kwargs: sampling kwargs (incl. inference-time ``beta``)
    """

    name: str = "ILQLConfig"
    tau: float = 0.7
    gamma: float = 0.99
    cql_scale: float = 0.1
    awac_scale: float = 1.0
    alpha: float = 0.001
    beta: float = 0.0
    steps_for_target_q_sync: int = 5
    two_qs: bool = True
    gen_kwargs: Dict[str, Any] = field(default_factory=dict)

    def loss(
        self,
        logits: jax.Array,  # [B, A, V] logits at action positions
        qs: Tuple[jax.Array, ...],  # each [B, A, V]
        target_qs: Tuple[jax.Array, ...],  # each [B, A, V]
        vs: jax.Array,  # [B, S, 1] values at state positions
        actions: jax.Array,  # [B, A] action token ids
        rewards: jax.Array,  # [B, A]
        dones: jax.Array,  # [B, S]
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """ILQL objective.

        L = Σ_i (Q_i − (r + γ·V'))² (expectile-free TD on each Q head)
          + expectile_τ(minQ' − V)
          + cql_scale · Σ_i CE(q_i, a)
          + awac_scale · exp(β(minQ' − V)) · CE(logits, a)
        masked by ``dones[:, :-1]`` (non-terminal steps), mean over
        non-terminal count. Matches ``modeling_ilql.py:60-132``.
        """
        logits = logits.astype(jnp.float32)
        vs = vs.astype(jnp.float32)
        terminal_mask = dones[:, :-1].astype(jnp.float32)  # [B, A]
        n_nonterminal = jnp.maximum(terminal_mask.sum(), 1.0)
        bsize, nactions, dsize = logits.shape

        actions_exp = actions[..., None]  # [B, A, 1]
        Q = [
            jnp.take_along_axis(q.astype(jnp.float32), actions_exp, axis=-1)[..., 0]
            for q in qs
        ]
        targetQs = [
            jax.lax.stop_gradient(
                jnp.take_along_axis(q.astype(jnp.float32), actions_exp, axis=-1)[..., 0]
            )
            for q in target_qs
        ]
        targetQ = targetQs[0]
        for tq in targetQs[1:]:
            targetQ = jnp.minimum(targetQ, tq)

        V = vs[:, :-1, 0]  # [B, A] value of current states
        Vnext = vs[:, 1:, 0] * dones[:, 1:].astype(vs.dtype)
        Q_target = rewards + self.gamma * jax.lax.stop_gradient(Vnext)

        loss_qs = [
            jnp.sum(jnp.square(Qi - Q_target) * terminal_mask) / n_nonterminal
            for Qi in Q
        ]
        loss_q = sum(loss_qs)

        # expectile loss on V towards min target-Q
        diff = targetQ - V
        weight = jnp.where(diff >= 0, self.tau, 1.0 - self.tau)
        loss_v = jnp.sum(weight * jnp.square(diff) * terminal_mask) / n_nonterminal

        def ce(logit_like):  # [B, A, V] vs actions [B, A] → [B, A]
            logp = jax.nn.log_softmax(logit_like.astype(jnp.float32), axis=-1)
            return -jnp.take_along_axis(logp, actions_exp, axis=-1)[..., 0]

        loss_cql = sum(
            jnp.sum(ce(q) * terminal_mask) / n_nonterminal for q in qs
        )

        awac_weight = jax.lax.stop_gradient(jnp.exp(self.beta * (targetQ - V)))
        loss_awac = jnp.sum(ce(logits) * awac_weight * terminal_mask) / n_nonterminal

        loss = loss_q + loss_v + self.cql_scale * loss_cql + self.awac_scale * loss_awac

        dist = {}
        if self.dist_sketches:
            from trlx_tpu.observability.dynamics import entropy_of_logits, loss_sketches

            # TD error of the first Q head as the value-error sketch, the
            # expectile target gap (minQ' − V) as the advantage analogue
            dist = loss_sketches(
                {
                    "value_error": (Q[0] - Q_target, terminal_mask),
                    "advantages": (diff, terminal_mask),
                    "entropy": (entropy_of_logits(logits), terminal_mask),
                }
            )

        stats = dict(
            **dist,
            losses=dict(
                loss=loss,
                loss_q=loss_q,
                loss_v=loss_v,
                loss_cql=loss_cql,
                loss_awac=loss_awac,
            ),
            values=get_tensor_stats(V, terminal_mask, n_nonterminal),
            qvalues={
                str(ix): get_tensor_stats(Q[ix], terminal_mask, n_nonterminal)
                for ix in range(len(Q))
            },
            awac_weight=get_tensor_stats(awac_weight, terminal_mask, n_nonterminal),
        )
        return loss, flatten_dict(stats)
