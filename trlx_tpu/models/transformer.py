"""TPU-native causal transformer backbone (Flax linen).

One configurable decoder covers the reference's supported causal families —
GPT-2, GPT-J, GPT-NeoX/Pythia, OPT, BLOOM, LLaMA (reference wraps HF models:
``trlx/models/modeling_ppo.py:429-946``) — via architecture flags (positional
scheme, norm type, activation, parallel-residual, biases, GQA).

TPU-first design decisions:
- every weight carries **logical axis names** (``nn.with_logical_partitioning``)
  so one set of sharding rules (``trlx_tpu/parallel``) maps the whole model
  onto a ``(data, pipe, fsdp, model, sequence)`` mesh — the GSPMD equivalent of
  Megatron TP/SP in the reference's NeMo backend;
- **explicit functional KV cache** (a pytree threaded through the decode
  loop) instead of stateful modules, so generation is one compiled
  ``lax.while_loop`` program;
- static shapes everywhere: padding is handled by masks, positions are
  computed from the mask (left-padded prompts attend correctly);
- optional ``remat`` and ``scan_layers`` for memory/compile scaling.
"""

import dataclasses
import functools
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

def param_with_axes(init: Callable, axes: Tuple[str, ...]) -> Callable:
    """Logical axes of each parameter are derived from its *path* by the rule
    table in ``trlx_tpu/parallel/sharding.py`` (path-based, à la t5x), so the
    param tree stays plain jax arrays (no flax Partitioned boxes) — plain
    trees keep the optimizer, HF interop, and checkpoint layers trivial. The
    ``axes`` argument documents intent at the definition site and is asserted
    against the rule table in tests."""
    del axes
    return init


def _maybe_pipeline_mesh(cfg: "TransformerConfig"):
    """The global mesh, iff its ``pipe`` axis should pipeline this model's
    block stack (requires ``scan_layers``: the stacked params are what shards
    across stages)."""
    from trlx_tpu.parallel.mesh import get_global_mesh

    mesh = get_global_mesh()
    if mesh is None or mesh.shape.get("pipe", 1) <= 1:
        return None
    if cfg.ignore_pipe_mesh:
        return None
    if not cfg.scan_layers:
        raise ValueError(
            "pipeline parallelism (mesh pipe>1) requires scan_layers=True — "
            "the stacked block params are what shards across stages"
        )
    if cfg.num_layers % mesh.shape["pipe"]:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pipe stages "
            f"{mesh.shape['pipe']}"
        )
    return mesh


def _traced_global_mesh():
    """The global mesh, iff one is set AND we are inside a trace (sharding
    constraints / collective layouts only apply under jit; eager passes —
    e.g. ``module.init`` — take the plain paths)."""
    from trlx_tpu.parallel.mesh import get_global_mesh

    try:
        from jax._src.core import trace_state_clean
    except ImportError:  # pragma: no cover - private API moved

        def trace_state_clean():
            return False

    mesh = get_global_mesh()
    if mesh is not None and not trace_state_clean():
        return mesh
    return None


def _activation_sharded(x):
    """Pin the weight-stationary decode layout on a ``[B, 1, D]`` embedding
    output: batch over ``data``, hidden over ``fsdp``, seq untouched. The
    hidden shards line up with the ``(fsdp, model)`` kernel sharding's
    contracted dim, so every block matmul in the decode loop is a local
    partial + a tiny ``[B,1,D]`` all-reduce and the multi-GB weights never
    move.

    Applied at the embedding output of single-token decode steps ONLY: the
    vocab-parallel ``wte`` gather otherwise leaves the partitioner free to
    pick a conflicting layout for the lookup result inside the decode
    ``while`` loop, which it then cannot reconcile with the loop body's
    layout without an involuntary full rematerialization
    (``spmd_partitioner.cc`` replicate-then-repartition) on every step. Full
    forwards (prefill / score / train) are deliberately left unconstrained —
    there the partitioner's propagated layout avoids per-layer fsdp weight
    all-gathers entirely (measured: constraining them trades -33% flops for
    +130% bytes_accessed on the 6B fsdp2·tp2·sp2 budget, a net loss on the
    HBM-bound programs), and no remat warning is emitted on those paths.
    """
    mesh = _traced_global_mesh()
    if mesh is None or x.ndim != 3 or x.shape[1] != 1:
        return x
    if mesh.shape.get("pipe", 1) > 1:
        # the pipeline engine re-lays activations into its stage-resident
        # [S, mb, T, E] buffer immediately after embed and constrains that
        # buffer itself (parallel/pipeline.py::tick); a conflicting spec here
        # just forces a reshard at the injection slice
        return x
    from trlx_tpu.parallel.sharding import constrain_activation

    return constrain_activation(x, mesh, "data", None, "fsdp")


def _maybe_ring_mesh(T: int):
    """The traced mesh, iff its ``sequence`` axis should carry this pass
    (full self-attention forwards, ALiBi included; ring doesn't apply to
    cache decode — plain flash handles that, with GSPMD gathering K/V if
    activations are sequence-sharded)."""
    mesh = _traced_global_mesh()
    if (
        mesh is not None
        and mesh.shape.get("sequence", 1) > 1
        and T % mesh.shape["sequence"] == 0
    ):
        return mesh
    return None


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Architecture description of a causal decoder-only transformer."""

    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    intermediate_size: int
    max_position_embeddings: int = 2048
    num_kv_heads: Optional[int] = None  # < num_heads → grouped-query attention
    head_dim: Optional[int] = None

    # HF family tag ("gpt2", "llama", "gpt_neox", "gptj", "opt", "bloom");
    # selects the import/export converter pair in hf_interop
    model_type: Optional[str] = None

    position_scheme: str = "learned"  # learned | rotary | alibi
    pos_offset: int = 0  # OPT stores positions with an offset of 2
    rotary_dim: Optional[int] = None  # partial rotary (gptj/neox); None = full
    rope_theta: float = 10000.0

    # sliding-window attention (mistral family): each query attends only the
    # last `sliding_window` positions. None = unbounded full causal. Slots
    # are temporally ordered with padding only on the left, so the window is
    # enforced on slot distance in every path (xla bias, flash kernel, ring).
    sliding_window: Optional[int] = None

    norm: str = "layernorm"  # layernorm | rmsnorm
    layer_norm_epsilon: float = 1e-5
    activation: str = "gelu_new"  # gelu_new | gelu | silu | relu
    parallel_residual: bool = False  # gptj/neox style
    shared_ln: bool = False  # gptj: one LN feeds both attn and mlp
    attn_bias: bool = True
    mlp_bias: bool = True
    qkv_bias: Optional[bool] = None  # overrides attn_bias for q/k/v if set
    tie_word_embeddings: bool = True
    final_norm: bool = True
    embedding_layernorm: bool = False  # bloom has a LN after word embeddings
    lm_head_bias: bool = False  # gptj has a bias on the lm head

    # numerics / compilation
    param_dtype: Any = jnp.float32
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    remat: str = "none"  # none | minimal | full
    scan_layers: bool = False
    # run unpipelined even when the global mesh has pipe > 1: the model
    # computes replicated across pipeline stages instead of through the
    # GPipe schedule. For small auxiliary models that ride a big model's
    # mesh — e.g. the speculative-decoding draft, which runs replicated
    # while the pipelined target verifies its proposals.
    ignore_pipe_mesh: bool = False
    # attention implementation: "auto" (pallas flash kernel on TPU, xla
    # elsewhere), "xla" (dot-product, XLA-fused), or "pallas" (force flash)
    attention_impl: str = "auto"

    # LoRA (reference: OpenDelta lora via ``model.peft_kwargs``,
    # ``trlx/utils/modeling.py:389-450``). r=0 disables. Adapters are created
    # on every matching projection; the trainable mask keeps only the
    # unfrozen-layer range learnable, which matches the reference's
    # layer-ranged modified_modules regex with zero-init B making the rest
    # exact no-ops.
    lora_r: int = 0
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ()

    # pipeline parallelism: microbatches per GPipe round when the mesh has a
    # pipe axis > 1 (0 = auto: one per stage). See parallel/pipeline.py.
    pipe_microbatches: int = 0

    # mixture-of-experts MLP (mixtral family; beyond the reference, which has
    # no MoE — SURVEY.md §2.3 lists EP as n/a). 0 = dense MLP. Experts are
    # GShard-style einsum dispatch with a per-sequence token group and a
    # static capacity; expert weights shard over the mesh's `expert` axis
    # (parallel/mesh.py) so XLA inserts the token all_to_alls.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 1.25  # slots per expert = ceil(k*G*cf/E)
    moe_group_size: int = 0  # dispatch group tokens (0 = whole sequence);
    # bounds the [.., E, C] slot tensors to O(T·G) instead of O(T²)
    moe_renormalize: bool = True  # mixtral renormalizes the top-k gate probs
    router_aux_coef: float = 0.01  # load-balance loss weight (Switch-style)
    router_z_coef: float = 0.0  # router logit z-loss weight (ST-MoE)

    def resolved_attention_impl(self) -> str:
        if self.attention_impl == "auto":
            return "pallas" if jax.default_backend() == "tpu" else "xla"
        return self.attention_impl

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dims_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    # ---- family presets (sizes per the public model cards) ----

    @staticmethod
    def gpt2(size: str = "small", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=256, max_position_embeddings=128),
            "small": dict(vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072, max_position_embeddings=1024),
            "medium": dict(vocab_size=50257, hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096, max_position_embeddings=1024),
            "large": dict(vocab_size=50257, hidden_size=1280, num_layers=36, num_heads=20, intermediate_size=5120, max_position_embeddings=1024),
            "xl": dict(vocab_size=50257, hidden_size=1600, num_layers=48, num_heads=25, intermediate_size=6400, max_position_embeddings=1024),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="gpt2",
            position_scheme="learned",
            norm="layernorm",
            activation="gelu_new",
            tie_word_embeddings=True,
        )

    @staticmethod
    def llama(size: str = "7b", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2, intermediate_size=128, max_position_embeddings=128),
            "7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32, intermediate_size=11008, max_position_embeddings=2048),
            "13b": dict(vocab_size=32000, hidden_size=5120, num_layers=40, num_heads=40, intermediate_size=13824, max_position_embeddings=2048),
            "65b": dict(vocab_size=32000, hidden_size=8192, num_layers=80, num_heads=64, intermediate_size=22016, max_position_embeddings=2048),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="llama",
            position_scheme="rotary",
            norm="rmsnorm",
            layer_norm_epsilon=1e-6,
            activation="silu",
            attn_bias=False,
            mlp_bias=False,
            tie_word_embeddings=False,
        )

    @staticmethod
    def mistral(size: str = "7b", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2, intermediate_size=128, max_position_embeddings=128, sliding_window=8),
            "7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=8, intermediate_size=14336, max_position_embeddings=32768, sliding_window=4096),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="mistral",
            position_scheme="rotary",
            norm="rmsnorm",
            layer_norm_epsilon=1e-5,
            activation="silu",
            attn_bias=False,
            mlp_bias=False,
            tie_word_embeddings=False,
        )

    @staticmethod
    def mixtral(size: str = "8x7b", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2, intermediate_size=96, max_position_embeddings=128, num_experts=4),
            "8x7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=8, intermediate_size=14336, max_position_embeddings=32768, num_experts=8, moe_group_size=512),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="mixtral",
            position_scheme="rotary",
            rope_theta=1e6,
            norm="rmsnorm",
            layer_norm_epsilon=1e-5,
            activation="silu",
            attn_bias=False,
            mlp_bias=False,
            tie_word_embeddings=False,
            num_experts_per_tok=2,
        )

    @staticmethod
    def gptj(size: str = "6b", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=256, max_position_embeddings=128),
            "6b": dict(vocab_size=50400, hidden_size=4096, num_layers=28, num_heads=16, intermediate_size=16384, max_position_embeddings=2048),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="gptj",
            position_scheme="rotary",
            rotary_dim=64 if size != "test" else 8,
            norm="layernorm",
            activation="gelu_new",
            parallel_residual=True,
            shared_ln=True,
            attn_bias=False,
            qkv_bias=False,
            mlp_bias=True,
            tie_word_embeddings=False,
            lm_head_bias=True,
        )

    @staticmethod
    def gptneox(size: str = "160m", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=256, max_position_embeddings=128),
            "160m": dict(vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072, max_position_embeddings=2048),
            "1.4b": dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16, intermediate_size=8192, max_position_embeddings=2048),
            "6.9b": dict(vocab_size=50432, hidden_size=4096, num_layers=32, num_heads=32, intermediate_size=16384, max_position_embeddings=2048),
            "20b": dict(vocab_size=50432, hidden_size=6144, num_layers=44, num_heads=64, intermediate_size=24576, max_position_embeddings=2048),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="gpt_neox",
            position_scheme="rotary",
            rotary_dim=(dims["hidden_size"] // dims["num_heads"]) // 4 if size != "test" else 4,
            norm="layernorm",
            activation="gelu",
            parallel_residual=True,
            shared_ln=False,
            attn_bias=True,
            mlp_bias=True,
            tie_word_embeddings=False,
        )

    @staticmethod
    def opt(size: str = "125m", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=256, max_position_embeddings=128),
            "125m": dict(vocab_size=50272, hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072, max_position_embeddings=2048),
            "6.7b": dict(vocab_size=50272, hidden_size=4096, num_layers=32, num_heads=32, intermediate_size=16384, max_position_embeddings=2048),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="opt",
            position_scheme="learned",
            pos_offset=2,
            norm="layernorm",
            activation="relu",
            tie_word_embeddings=True,
        )

    @staticmethod
    def bloom(size: str = "560m", **overrides) -> "TransformerConfig":
        dims = {
            "test": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4, intermediate_size=256, max_position_embeddings=128),
            "560m": dict(vocab_size=250880, hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096, max_position_embeddings=2048),
        }[size]
        return _make_preset(
            dims,
            overrides,
            model_type="bloom",
            position_scheme="alibi",
            norm="layernorm",
            activation="gelu",
            embedding_layernorm=True,
            tie_word_embeddings=True,
        )



def _make_preset(dims: dict, overrides: dict, **flags) -> "TransformerConfig":
    """Build a preset config: dims + family flags, with ``overrides`` able to
    replace ANY field (dimension or architecture flag) without conflicts."""
    base = {**dims, **flags}
    base.update(overrides)
    return TransformerConfig(**base)

def get_activation(name: str) -> Callable:
    return {
        "gelu_new": partial(nn.gelu, approximate=True),
        "gelu": partial(nn.gelu, approximate=False),
        "silu": nn.silu,
        "relu": nn.relu,
    }[name]


# ---------------------------------------------------------------------------
# Positional encodings
# ---------------------------------------------------------------------------


def rotary_sin_cos(positions: jax.Array, dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """sin/cos tables for RoPE at integer ``positions`` [B, T] → [B, T, dim/2]."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    freqs = positions[..., None].astype(jnp.float32) * inv_freq  # [B, T, dim/2]
    return jnp.sin(freqs), jnp.cos(freqs)


def apply_rotary(x: jax.Array, sin: jax.Array, cos: jax.Array, rotary_dim: int, neox_style: bool) -> jax.Array:
    """Apply RoPE to the first ``rotary_dim`` dims of x [B, T, H, D].

    ``neox_style=True`` rotates split halves (llama/neox); False rotates
    interleaved even/odd pairs (gptj).
    """
    x_rot, x_pass = x[..., :rotary_dim], x[..., rotary_dim:]
    sin = sin[:, :, None, :]  # [B, T, 1, dim/2]
    cos = cos[:, :, None, :]
    if neox_style:
        half = rotary_dim // 2
        x1, x2 = x_rot[..., :half], x_rot[..., half:]
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    else:
        x1, x2 = x_rot[..., ::2], x_rot[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        out = jnp.stack([r1, r2], axis=-1).reshape(x_rot.shape)
    return jnp.concatenate([out, x_pass], axis=-1).astype(x.dtype)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """ALiBi per-head slopes (Press et al.), matching the BLOOM recipe."""

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    if np.log2(num_heads).is_integer():
        return pow2_slopes(num_heads)
    closest = 2 ** int(np.floor(np.log2(num_heads)))
    base = pow2_slopes(closest)
    extra = pow2_slopes(2 * closest)[0::2][: num_heads - closest]
    return np.concatenate([base, extra])


# ---------------------------------------------------------------------------
# Modules
# ---------------------------------------------------------------------------


def Norm(config: TransformerConfig, name: str):
    """LayerNorm/RMSNorm with params directly at ``<name>/{scale,bias}``."""
    cls = nn.RMSNorm if config.norm == "rmsnorm" else nn.LayerNorm
    kwargs = {}
    if config.norm != "rmsnorm":
        kwargs["bias_init"] = param_with_axes(nn.initializers.zeros, ("embed",))
    return cls(
        epsilon=config.layer_norm_epsilon,
        dtype=config.dtype,
        param_dtype=config.param_dtype,
        scale_init=param_with_axes(nn.initializers.ones, ("embed",)),
        name=name,
        **kwargs,
    )


class LoRADense(nn.Module):
    """Dense with an additive low-rank branch: ``y = xW (+b) + (alpha/r)·xAB``.

    Parameters live at the same tree level as a plain Dense (``kernel``/
    ``bias`` plus ``lora_a``/``lora_b``), so HF import and the path-based
    sharding rules are unchanged. ``lora_b`` is zero-init: the module is an
    exact no-op until trained."""

    features: int
    use_bias: bool
    dtype: Any
    param_dtype: Any
    kernel_init: Callable
    bias_init: Callable
    r: int
    alpha: float

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (in_features, self.features), self.param_dtype)
        y = x @ kernel.astype(self.dtype)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        a = self.param("lora_a", nn.initializers.he_uniform(), (in_features, self.r), self.param_dtype)
        b = self.param("lora_b", nn.initializers.zeros, (self.r, self.features), self.param_dtype)
        scale = self.alpha / self.r
        y = y + (x @ a.astype(self.dtype)) @ b.astype(self.dtype) * scale
        return y


def _dense(cfg, features, use_bias, kernel_axes, name=None):
    kernel_init = param_with_axes(nn.initializers.normal(0.02), kernel_axes)
    bias_init = param_with_axes(nn.initializers.zeros, (kernel_axes[-1],))
    if getattr(cfg, "lora_r", 0) and name in getattr(cfg, "lora_targets", ()):
        return LoRADense(
            features,
            use_bias,
            cfg.dtype,
            cfg.param_dtype,
            kernel_init,
            bias_init,
            cfg.lora_r,
            cfg.lora_alpha,
            name=name,
        )
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=kernel_init,
        bias_init=bias_init,
        name=name,
    )


class Attention(nn.Module):
    """Multi-head / grouped-query attention with RoPE/ALiBi and an explicit
    KV cache ({"k","v"} arrays [B, S, kvH, D] written at ``cache_index``)."""

    config: TransformerConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,  # [B, T, E]
        attention_bias: Optional[jax.Array],  # [B, 1, T, S] additive (xla path)
        positions: jax.Array,  # [B, T]
        cache: Optional[Dict[str, jax.Array]] = None,
        cache_index: Optional[jax.Array] = None,
        flash_args: Optional[Dict[str, Any]] = None,  # pallas path (see below)
    ):
        cfg = self.config
        B, T, _ = x.shape
        H, KV, D = cfg.num_heads, cfg.kv_heads, cfg.dims_per_head
        qkv_bias = cfg.attn_bias if cfg.qkv_bias is None else cfg.qkv_bias

        q = _dense(cfg, H * D, qkv_bias, ("embed", "joined_kv"), "q_proj")(x).reshape(B, T, H, D)
        k = _dense(cfg, KV * D, qkv_bias, ("embed", "joined_kv"), "k_proj")(x).reshape(B, T, KV, D)
        v = _dense(cfg, KV * D, qkv_bias, ("embed", "joined_kv"), "v_proj")(x).reshape(B, T, KV, D)

        if cfg.position_scheme == "rotary":
            rdim = cfg.rotary_dim or D
            sin, cos = rotary_sin_cos(positions, rdim, cfg.rope_theta)
            neox = cfg.norm == "rmsnorm" or not cfg.shared_ln  # llama/neox vs gptj
            q = apply_rotary(q, sin, cos, rdim, neox)
            k = apply_rotary(k, sin, cos, rdim, neox)

        paged = cache is not None and isinstance(cache, dict) and "block_table" in cache
        if paged:
            # in-place paged attention (ops/paged_attention.py single-token
            # decode; ops/paged_prefill.py chunked prefill): K/V live in
            # the block pool ({"k","v"} over [NB, bs, KV, D]) and this
            # call's k/v commit straight through the per-row block table —
            # no gathered dense view exists, before or after. Drop-mode
            # writes make poisoned (out-of-range) table rows — frozen slots,
            # padding lanes — write nothing, mirroring scatter_steps'/
            # scatter_span's live-writes-only commit on the gather path.
            table = cache["block_table"]
            ci = jnp.asarray(cache_index if cache_index is not None else 0)
            blk_size = cache["k"].shape[-3]
            if T == 1:
                if ci.ndim == 0:
                    ci = jnp.broadcast_to(ci, (B,))
                blk = jnp.take_along_axis(table, (ci // blk_size)[:, None], axis=1)[:, 0]
                off = ci % blk_size
                k_pool = cache["k"].at[blk, off].set(
                    k[:, 0].astype(cache["k"].dtype), mode="drop"
                )
                v_pool = cache["v"].at[blk, off].set(
                    v[:, 0].astype(cache["v"].dtype), mode="drop"
                )
                new_cache = {"k": k_pool, "v": v_pool, "block_table": table}
                from trlx_tpu.ops.paged_attention import paged_attention_decode

                # the additive bias rows carry the full masking semantics
                # (slot-causal + key validity + window/ALiBi) — identical to
                # what the dense einsum path would consume. The head dim is 1
                # (mask-only) or H (per-head ALiBi slopes) and is preserved.
                out = paged_attention_decode(
                    q[:, 0], k_pool, v_pool, table, attention_bias[:, :, 0, :]
                ).reshape(B, 1, H * D)
            else:
                # multi-position span. Two callers land here:
                #   * prefill chunk — all rows share one static span
                #     [ci, ci+T) (the refill/chunk programs group rows per
                #     span), so ci is a scalar and the commit columns are a
                #     [T] vector broadcast over rows;
                #   * speculative verify — the target scores gamma+1 probe
                #     positions per row at per-row depths (rows rewind to
                #     different accepted lengths), so ci is a [B] vector and
                #     each row writes its own [T] column window.
                # Either way every row writes through its own table's
                # blocks; shared prefix blocks sit strictly below ci and
                # are only ever read.
                verify = ci.ndim != 0
                if verify:
                    cols = ci[:, None] + jnp.arange(T)[None, :]  # [B, T]
                    blk = jnp.take_along_axis(table, cols // blk_size, axis=1)
                    off = cols % blk_size
                else:
                    cols = ci + jnp.arange(T)  # [T]
                    blk = table[:, cols // blk_size]  # [B, T]
                    off = jnp.broadcast_to((cols % blk_size)[None, :], blk.shape)
                k_pool = cache["k"].at[blk, off].set(
                    k.astype(cache["k"].dtype), mode="drop"
                )
                v_pool = cache["v"].at[blk, off].set(
                    v.astype(cache["v"].dtype), mode="drop"
                )
                new_cache = {"k": k_pool, "v": v_pool, "block_table": table}
                if verify:
                    from trlx_tpu.ops.paged_attention import (
                        paged_verify_attention,
                    )

                    out = paged_verify_attention(
                        q, k_pool, v_pool, table, attention_bias
                    ).reshape(B, T, H * D)
                else:
                    from trlx_tpu.ops.paged_prefill import (
                        paged_prefill_attention,
                    )

                    out = paged_prefill_attention(
                        q, k_pool, v_pool, table, attention_bias
                    ).reshape(B, T, H * D)
            out = _dense(cfg, cfg.hidden_size, cfg.attn_bias, ("joined_kv", "embed"), "o_proj")(out)
            return out, new_cache

        new_cache = None
        if cache is not None:
            # decode: write this step's k/v into the cache at cache_index —
            # a scalar (all rows aligned) or a [B] vector (speculative
            # decoding: rows rewind to different accepted lengths)
            ci = jnp.asarray(cache_index)
            if ci.ndim == 0:
                k_cache = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype), (0, ci, 0, 0))
                v_cache = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype), (0, ci, 0, 0))
            else:
                row_write = jax.vmap(
                    lambda c, x, i: jax.lax.dynamic_update_slice(c, x, (i, 0, 0))
                )
                k_cache = row_write(cache["k"], k.astype(cache["k"].dtype), ci)
                v_cache = row_write(cache["v"], v.astype(cache["v"].dtype), ci)
            k, v = k_cache, v_cache
            new_cache = {"k": k_cache, "v": v_cache}

        ring_mesh = None
        if flash_args is not None and cache is None:
            ring_mesh = _maybe_ring_mesh(T)
        if ring_mesh is not None:
            # sequence-parallel exact attention: K/V chunks rotate around the
            # mesh's ``sequence`` ring with zigzag causal placement (context
            # parallelism; beyond the reference, which caps seq_length
            # instead — SURVEY.md §5). ALiBi rides the ring as true token
            # positions.
            from trlx_tpu.parallel.ring_attention import ring_flash_attention

            out = ring_flash_attention(
                q, k, v, flash_args["key_mask"], ring_mesh,
                q_positions=flash_args.get("q_positions"),
                k_positions=flash_args.get("k_positions"),
                alibi_slopes=flash_args.get("alibi_slopes"),
                window=flash_args.get("window"),
            ).reshape(B, T, H * D)
        elif flash_args is not None:
            # fused flash-attention kernel; masking semantics identical to the
            # additive-bias path (slot-causal + key validity + optional ALiBi)
            from trlx_tpu.ops.flash_attention import flash_attention

            out = flash_attention(
                q,
                k,
                v,
                flash_args["key_mask"],
                causal=True,
                q_offset=flash_args.get("q_offset", 0),
                q_positions=flash_args.get("q_positions"),
                k_positions=flash_args.get("k_positions"),
                alibi_slopes=flash_args.get("alibi_slopes"),
                window=flash_args.get("window"),
            ).reshape(B, T, H * D)
        else:
            if KV < H:  # flash/ring kernels consume unrepeated K/V (GQA-aware)
                k = jnp.repeat(k, H // KV, axis=2)
                v = jnp.repeat(v, H // KV, axis=2)
            depth = jnp.asarray(D, cfg.dtype)
            scores = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.sqrt(depth)
            scores = scores + attention_bias.astype(scores.dtype)
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(B, T, H * D)
        out = _dense(cfg, cfg.hidden_size, cfg.attn_bias, ("joined_kv", "embed"), "o_proj")(out)
        return out, new_cache


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        act = get_activation(cfg.activation)
        if cfg.activation == "silu":  # gated (llama-style) MLP
            gate = _dense(cfg, cfg.intermediate_size, cfg.mlp_bias, ("embed", "ffn"), "gate_proj")(x)
            up = _dense(cfg, cfg.intermediate_size, cfg.mlp_bias, ("embed", "ffn"), "up_proj")(x)
            h = act(gate) * up
        else:
            h = act(_dense(cfg, cfg.intermediate_size, cfg.mlp_bias, ("embed", "ffn"), "up_proj")(x))
        return _dense(cfg, cfg.hidden_size, cfg.mlp_bias, ("ffn", "embed"), "down_proj")(h)


@functools.lru_cache(maxsize=None)
def _warn_indivisible_experts(num_experts: int, axis: int) -> None:
    """Warn ONCE per (experts, axis) pair: the divisibility fit silently
    drops the expert axis, so expert-parallel dispatch degrades to replicated
    compute — a throughput cliff that deserves a diagnosis line (same
    contract as ``pipeline.py::pick_microbatches``). lru_cache keeps it to
    one line instead of one per layer per trace per recompile."""
    from trlx_tpu.utils import logging

    logging.get_logger(__name__).warning(
        "num_experts %d not divisible by mesh expert axis %d: expert-parallel "
        "dispatch runs replicated — resize the expert axis or the expert "
        "count to recover EP",
        num_experts, axis,
    )


def _maybe_expert_mesh():
    """The traced mesh, iff its ``expert`` axis actually partitions experts
    (size > 1)."""
    mesh = _traced_global_mesh()
    if mesh is not None and mesh.shape.get("expert", 1) > 1:
        return mesh
    return None


class MoEMLP(nn.Module):
    """Mixture-of-experts MLP: top-k router + GShard-style einsum dispatch.

    TPU-first design (the reference has no MoE at all — SURVEY.md §2.3 lists
    EP as n/a; this is a beyond-parity capability for the mixtral family):

    - each sequence is a dispatch group: tokens route to their top-k experts
      with a *static* per-group capacity ``C = ceil(k·T·cf/E)`` (first
      choices claim slots before second choices; overflow tokens fall back to
      the residual path). Static shapes keep the whole thing one XLA program
      — no sorting, no dynamic gather.
    - expert weights carry a leading ``E`` dim sharded over the mesh's
      ``expert`` axis; the dispatch/combine einsums change token layout from
      batch-sharded to expert-sharded and back, which GSPMD lowers to
      all_to_all over the ``expert`` axis (the EP analogue of Megatron TP's
      allreduce). Per-expert matmul dims still shard over ``fsdp``/``model``.
    - the router runs in fp32; returns ``(y, aux)`` where ``aux`` is
      ``[load_balance, router_z]`` — the Switch-style balance loss
      (≡ 1.0 at a perfectly uniform router) and the ST-MoE z-loss.

    At decode (T = 1) the capacity is ``max(1, ceil(k·cf/E)) ≥ 1`` and top-k
    indices are distinct, so decode never drops tokens.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(
        self, x: jax.Array, token_mask: Optional[jax.Array] = None
    ) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        E, K = cfg.num_experts, cfg.num_experts_per_tok
        B, T, d = x.shape
        f = cfg.intermediate_size
        act = get_activation(cfg.activation)
        gated = cfg.activation == "silu"

        logits = nn.Dense(
            E,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=param_with_axes(nn.initializers.normal(0.02), ("embed", "expert_sel")),
            name="router",
        )(x.astype(jnp.float32))  # [B, T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, idx = jax.lax.top_k(probs, K)  # [B, T, K]
        if cfg.moe_renormalize:
            gate_vals = gate_vals / jnp.maximum(
                jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
            )

        # dispatch groups: capacity (and the [.., E, C] dispatch tensors)
        # scale with the group size G, not with T — whole-sequence groups
        # would make the slot tensors O(T²) per row at long context. G is
        # the largest divisor of T ≤ moe_group_size (static).
        G = T
        if cfg.moe_group_size > 0:
            G = min(cfg.moe_group_size, T)
            while T % G:
                G -= 1
        N = B * (T // G)
        xg = x.reshape(N, G, d)
        w = (
            jnp.ones((N, G), jnp.float32)
            if token_mask is None
            else token_mask.reshape(N, G).astype(jnp.float32)
        )

        C = max(1, int(np.ceil(K * G * cfg.moe_capacity_factor / E)))
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32).reshape(N, G, K, E)
        # padding tokens route nowhere: they claim no capacity slots and
        # leave the layer with zero output (the Block residual carries them)
        onehot = onehot * w[..., None, None].astype(jnp.int32)
        # slot assignment with choice-priority: every token's first choice
        # outranks any second choice (GShard top-2 semantics)
        perm = onehot.transpose(0, 2, 1, 3).reshape(N, K * G, E)
        pos = jnp.cumsum(perm, axis=1) - perm  # slots taken before this entry
        kept = perm * (pos < C)
        slot = jax.nn.one_hot(pos, C, dtype=jnp.float32) * kept[..., None]
        gates_perm = (
            gate_vals.reshape(N, G, K).transpose(0, 2, 1).reshape(N, K * G)
        )
        combine = (
            (slot * gates_perm[..., None, None]).reshape(N, K, G, E, C).sum(1)
        )  # [N, G, E, C] fp32
        dispatch = slot.reshape(N, K, G, E, C).sum(1)

        mesh = _maybe_expert_mesh()

        if mesh is not None and E % mesh.shape.get("expert", 1):
            _warn_indivisible_experts(E, mesh.shape.get("expert", 1))

        def expert_sharded(a):
            from trlx_tpu.parallel.sharding import constrain_activation

            return constrain_activation(a, mesh, "expert", ("data", "fsdp"))

        xin = jnp.einsum("ngd,ngec->encd", xg, dispatch.astype(x.dtype))
        xin = expert_sharded(xin)  # ← GSPMD inserts the dispatch all_to_all
        if gated:
            w_gate = self.param(
                "w_gate",
                param_with_axes(nn.initializers.normal(0.02), ("expert", "embed", "ffn")),
                (E, d, f),
                cfg.param_dtype,
            )
            w_up = self.param(
                "w_up",
                param_with_axes(nn.initializers.normal(0.02), ("expert", "embed", "ffn")),
                (E, d, f),
                cfg.param_dtype,
            )
            h = act(jnp.einsum("encd,edf->encf", xin, w_gate.astype(cfg.dtype)))
            h = h * jnp.einsum("encd,edf->encf", xin, w_up.astype(cfg.dtype))
        else:
            w_up = self.param(
                "w_up",
                param_with_axes(nn.initializers.normal(0.02), ("expert", "embed", "ffn")),
                (E, d, f),
                cfg.param_dtype,
            )
            h = act(jnp.einsum("encd,edf->encf", xin, w_up.astype(cfg.dtype)))
        w_down = self.param(
            "w_down",
            param_with_axes(nn.initializers.normal(0.02), ("expert", "ffn", "embed")),
            (E, f, d),
            cfg.param_dtype,
        )
        out = jnp.einsum("encf,efd->encd", h, w_down.astype(cfg.dtype))
        out = expert_sharded(out)
        y = jnp.einsum("encd,ngec->ngd", out, combine.astype(out.dtype))
        y = y.reshape(B, T, d)

        # Switch load-balance loss over pre-capacity assignments: E·Σ f_e·p_e
        # (1.0 when both routing fractions and router probs are uniform).
        # Means are over REAL tokens only — padding must not train the router.
        # Returned as token-weighted sufficient statistics [lb·w, Σw·lse², w]
        # so accumulation over layers / microbatches / pipeline stages stays
        # correctly weighted under uneven padding; ``router_aux_summary``
        # normalizes to [lb, z] at the forward's end.
        n_real = jnp.sum(w)
        denom = jnp.maximum(n_real, 1.0)
        wf = w.reshape(B, T)
        me = jnp.sum(probs * wf[..., None], axis=(0, 1)) / denom
        ce = jnp.sum(onehot.astype(jnp.float32), axis=(0, 1, 2)) / (denom * K)
        aux_lb = E * jnp.sum(me * ce)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [B, T]
        z_sum = jnp.sum((lse**2) * wf)
        return y.astype(cfg.dtype), jnp.stack([aux_lb * n_real, z_sum, n_real])


_ZERO_AUX = (3,)  # Block aux statistics: [lb·tokens, Σ tokens·lse², tokens]


def router_aux_summary(aux: jax.Array) -> jax.Array:
    """Accumulated per-layer aux statistics → ``[load_balance, router_z]``
    (token-weighted means; exact for the z-loss under any layer/microbatch/
    pipeline-stage accumulation, token-weighted for the balance loss — which
    is a product of per-group means and therefore has per-group semantics,
    like every microbatched MoE implementation)."""
    return aux[:2] / jnp.maximum(aux[2], 1.0)


def _cache_is_paged(cache) -> bool:
    """True when ``cache`` carries a block table (``paged_kv.attach_block_
    table``): a per-layer list of dicts, or the scanned stacked dict."""
    if cache is None:
        return False
    if isinstance(cache, dict):
        return "block_table" in cache
    if isinstance(cache, list):
        return any(
            isinstance(layer, dict) and "block_table" in layer
            for layer in cache
        )
    return False


def _query_slots(q_offset, B: int, T: int) -> jax.Array:
    """[B, T] slot indices of queries at ``q_offset`` (scalar, or [B] when
    rows sit at different cache depths — speculative decoding)."""
    off = jnp.asarray(q_offset)
    if off.ndim == 1:
        off = off[:, None]
    return jnp.broadcast_to(off + jnp.arange(T)[None, :], (B, T))


def _token_validity(slot_mask: jax.Array, q_offset, T: int) -> jax.Array:
    """[B, T] validity of the query tokens occupying cache slots
    ``[q_offset, q_offset + T)`` of a [B, S] slot mask."""
    qs = _query_slots(q_offset, slot_mask.shape[0], T)
    return jax.vmap(lambda m, q: m[q])(slot_mask, qs)


class Block(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, attention_bias, positions, cache=None, cache_index=None, flash_args=None, token_mask=None):
        cfg = self.config

        def run_mlp(h):
            if cfg.num_experts > 0:
                return MoEMLP(cfg, name="mlp")(h, token_mask)
            return MLP(cfg, name="mlp")(h), jnp.zeros(_ZERO_AUX, jnp.float32)

        h = Norm(cfg, name="ln_attn")(x)
        attn_out, new_cache = Attention(cfg, name="attn")(h, attention_bias, positions, cache, cache_index, flash_args)
        if cfg.parallel_residual:
            mlp_in = h if cfg.shared_ln else Norm(cfg, name="ln_mlp")(x)
            mlp_out, aux = run_mlp(mlp_in)
            x = x + attn_out + mlp_out
        else:
            x = x + attn_out
            h = Norm(cfg, name="ln_mlp")(x)
            mlp_out, aux = run_mlp(h)
            x = x + mlp_out
        return x, new_cache, aux


def _remat_policy(cfg: TransformerConfig):
    """Rematerialisation policy per ``cfg.remat``:

    - ``full``: save nothing — recompute the whole block in the backward
      (max memory saving, ~1/3 extra FLOPs; NeMo's ``activations_checkpoint
      _granularity: full``, ``megatron_20b.yaml:77-79``);
    - ``minimal``: save matmul outputs with batch dims (the MXU-expensive
      results), recompute cheap elementwise/norm ops only — NeMo's
      ``selective`` granularity.
    """
    if cfg.remat == "minimal":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    return None  # full: save nothing


def _block_cls(cfg: TransformerConfig):
    if cfg.remat in ("full", "minimal"):
        return nn.remat(Block, policy=_remat_policy(cfg))
    return Block


class _ScanBlockBody(nn.Module):
    """``nn.scan`` body: one Block step over the layer axis.

    Carry = (hidden states, branch-input buffer). ``branch_at`` is the layer
    index whose *input* activations feed the hydra reference branch (−1 =
    never); captured via ``where`` since scan has no data-dependent exits.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, carry, cache_layer, layer_idx, attention_bias, positions, cache_index, flash_args, branch_at, token_mask):
        x, branch_input, aux_sum = carry
        x_new, new_cache, aux = _block_cls(self.config)(self.config, name="block")(
            x, attention_bias, positions, cache_layer, cache_index, flash_args, token_mask
        )
        if branch_input is not None:  # static: only hydra passes pay for it
            branch_input = jnp.where(layer_idx == branch_at, x, branch_input)
        return (x_new, branch_input, aux_sum + aux), new_cache


class CausalTransformer(nn.Module):
    """Decoder-only LM. Methods:

    - ``__call__``: full forward → logits (+ final hidden, + intermediate
      hidden at ``branch_layer`` for the hydra reference branch, + updated
      cache during decode).
    - ``forward_branch``: run the top layers from ``branch_layer`` on given
      hidden states (the frozen-reference replay; reference hydra semantics,
      ``trlx/models/modeling_ppo.py:331-427``).
    """

    config: TransformerConfig

    def setup(self):
        cfg = self.config
        self.wte = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=param_with_axes(nn.initializers.normal(0.02), ("vocab", "embed")),
            name="wte",
        )
        if cfg.position_scheme == "learned":
            self.wpe = nn.Embed(
                cfg.max_position_embeddings + cfg.pos_offset,
                cfg.hidden_size,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                embedding_init=param_with_axes(nn.initializers.normal(0.02), ("seq", "embed")),
                name="wpe",
            )
        if cfg.embedding_layernorm:
            self.emb_ln = Norm(cfg, name="emb_ln")
        if cfg.scan_layers:
            # roll all blocks into one lax.scan over stacked params — one
            # traced/compiled block instead of L, O(1) compile time and
            # program size in depth (the 20B+ scale path; the reference
            # leans on NeMo/Megatron for this regime,
            # ``trlx/models/modeling_nemo_ilql.py:253+``)
            scan_cls = nn.scan(
                _ScanBlockBody,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(0, 0, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                out_axes=0,
                length=cfg.num_layers,
            )
            self.scan_blocks = scan_cls(cfg, name="h_scan")
            self.blocks = []
        else:
            block = _block_cls(cfg)
            self.blocks = [block(cfg, name=f"h_{i}") for i in range(cfg.num_layers)]
        if cfg.final_norm:
            self.ln_f = Norm(cfg, name="ln_f")
        if not cfg.tie_word_embeddings:
            self.lm_head = _dense(cfg, cfg.vocab_size, cfg.lm_head_bias, ("embed", "vocab"), "lm_head")

    def _logits(self, h):
        cfg = self.config
        if cfg.tie_word_embeddings:
            return self.wte.attend(h)
        return self.lm_head(h)

    def _embed(self, input_ids, positions):
        cfg = self.config
        x = _activation_sharded(self.wte(input_ids))
        if cfg.position_scheme == "learned":
            x = x + self.wpe(positions + cfg.pos_offset)
        if cfg.embedding_layernorm:
            x = self.emb_ln(x)
        return x

    def _attention_bias(self, key_mask, query_slots, query_positions):
        """Additive [B, 1, T, S] bias over key *slots*: slot-causal + padding
        (+ ALiBi on true token positions).

        Slots are laid out in input order (prompt slots first, generated slots
        after), so slot-causality ``key_slot <= query_slot`` IS temporal
        causality, for full passes (slots ≡ positions), cache prefill, and
        single-token decode alike. ``key_mask`` [B, S] marks written, non-pad
        slots; positions of key slots are recovered as ``cumsum(mask)-1``
        (left-padded prompts thus attend with correct relative distances).
        """
        cfg = self.config
        S = key_mask.shape[1]
        key_slots = jnp.arange(S)[None, None, :]  # [1, 1, S]
        visible = (key_slots <= query_slots[:, :, None]) & (key_mask[:, None, :] > 0)
        if cfg.sliding_window:
            # slot distance ≡ position distance (padding is left-only)
            visible = visible & (
                query_slots[:, :, None] - key_slots < cfg.sliding_window
            )
        bias = jnp.where(visible[:, None, :, :], 0.0, -1e9)
        if cfg.position_scheme == "alibi":
            slopes = jnp.asarray(alibi_slopes(cfg.num_heads), dtype=jnp.float32)
            key_pos = jnp.maximum(jnp.cumsum(key_mask, axis=1) - 1, 0)  # [B, S]
            dist = (key_pos[:, None, :] - query_positions[:, :, None]).astype(jnp.float32)
            alibi = slopes[None, :, None, None] * dist[:, None, :, :]
            bias = bias + jnp.where(visible[:, None, :, :], alibi, 0.0)
        return bias

    def _attn_inputs(
        self, key_mask, positions, q_offset, use_flash
    ) -> Tuple[Optional[jax.Array], Optional[Dict[str, Any]]]:
        """``(bias, flash_args)`` for one forward — the single definition of
        the masking semantics, shared by the unpipelined path, the hydra
        branch replay, and each pipeline stage. Queries occupy slots
        ``[q_offset, q_offset + T)`` (0 for full passes)."""
        if use_flash:
            return None, self._flash_args(key_mask, positions, q_offset=q_offset)
        B, T = positions.shape
        query_slots = _query_slots(q_offset, B, T)
        return self._attention_bias(key_mask, query_slots, positions), None

    def _flash_args(self, key_mask, query_positions, q_offset=0) -> Dict[str, Any]:
        """Inputs for the pallas flash-attention path: same masking semantics
        as ``_attention_bias`` but resolved inside the kernel (no [B,1,T,S]
        bias tensor is ever materialised)."""
        cfg = self.config
        args: Dict[str, Any] = {"key_mask": key_mask, "q_offset": q_offset}
        if cfg.sliding_window:
            args["window"] = cfg.sliding_window
        if cfg.position_scheme == "alibi":
            args["alibi_slopes"] = jnp.asarray(alibi_slopes(cfg.num_heads), jnp.float32)
            args["q_positions"] = query_positions
            args["k_positions"] = jnp.maximum(jnp.cumsum(key_mask, axis=1) - 1, 0)
        return args

    def __call__(
        self,
        input_ids: jax.Array,  # [B, T]
        attention_mask: Optional[jax.Array] = None,  # [B, T] (or [B, S] in decode)
        positions: Optional[jax.Array] = None,  # [B, T]
        cache: Optional[List[Dict[str, jax.Array]]] = None,
        cache_index: Optional[jax.Array] = None,
        branch_layer: Optional[int] = None,
        logits_span: Optional[Tuple[int, int]] = None,  # static [a, b): lm-head
        # projection restricted to these positions — the vocab matmul is the
        # single biggest op in PPO scoring/training forwards and only the
        # response span is consumed there
    ) -> Dict[str, Any]:
        cfg = self.config
        B, T = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, T), jnp.int32)
        if cache is None:
            # full pass: key slots are the input sequence itself
            if positions is None:
                positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
        else:
            # attention_mask is the [B, S] slot mask over the whole cache;
            # queries occupy slots [cache_index, cache_index + T)
            if positions is None:
                offset = cache_index if cache_index is not None else 0
                query_slots = _query_slots(offset, B, T)
                key_pos = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
                positions = jax.vmap(lambda kp, qs: kp[qs])(key_pos, query_slots)

        token_mask = None
        if cfg.num_experts > 0:
            # MoE routing must know which query tokens are real: padding
            # claims no expert capacity and trains no router statistics
            if cache is None:
                token_mask = attention_mask
            else:
                offset = cache_index if cache_index is not None else 0
                token_mask = _token_validity(attention_mask, offset, T)

        x = self._embed(input_ids, positions)
        # flash kernels take a scalar slot offset; per-row cache depths
        # (speculative decoding) go through the bias path (T is tiny there).
        # Paged (block-table-carrying) caches always take the bias path too:
        # the in-place paged kernels consume the additive bias rows, and
        # their bit-parity reference is the dense einsum path.
        vector_ci = cache_index is not None and jnp.asarray(cache_index).ndim > 0
        paged_cache = _cache_is_paged(cache)
        use_flash = (
            cfg.resolved_attention_impl() == "pallas"
            and T > 1
            and not vector_ci
            and not paged_cache
        )
        pipe_mesh = None if self.is_initializing() else _maybe_pipeline_mesh(cfg)
        if pipe_mesh is not None:
            x, branch_input, new_cache, aux = self._pipelined_blocks(
                pipe_mesh, x, attention_mask, positions, use_flash,
                cache, cache_index, branch_layer,
            )
            return self._epilogue(x, branch_input, new_cache, logits_span, aux)
        bias, flash_args = self._attn_inputs(
            attention_mask,
            positions,
            cache_index if cache is not None and cache_index is not None else 0,
            use_flash,
        )

        branch_input = None
        aux = jnp.zeros(_ZERO_AUX, jnp.float32)
        if cfg.scan_layers:
            branch_at = cfg.num_layers - branch_layer if branch_layer is not None else -1
            branch_buf0 = jnp.zeros_like(x) if branch_layer is not None else None
            (x, branch_buf, aux), new_cache = self.scan_blocks(
                (x, branch_buf0, aux),
                cache,  # stacked {"k": [L,B,S,KV,D], "v": ...} or None
                jnp.arange(cfg.num_layers),
                bias,
                positions,
                cache_index,
                flash_args,
                jnp.asarray(branch_at),
                token_mask,
            )
            if branch_layer is not None:
                branch_input = branch_buf
        else:
            new_cache = [] if cache is not None else None
            for i, block in enumerate(self.blocks):
                if branch_layer is not None and i == len(self.blocks) - branch_layer:
                    branch_input = x
                layer_cache = cache[i] if cache is not None else None
                x, updated, aux_i = block(x, bias, positions, layer_cache, cache_index, flash_args, token_mask)
                aux = aux + aux_i
                if cache is not None:
                    new_cache.append(updated)

        return self._epilogue(x, branch_input, new_cache, logits_span, aux)

    def _epilogue(self, x, branch_input, new_cache, logits_span, aux=None):
        """Shared forward tail: final norm + (span-restricted) lm head."""
        cfg = self.config
        h = self.ln_f(x) if cfg.final_norm else x
        logits = self._logits(h if logits_span is None else h[:, logits_span[0] : logits_span[1]])
        out = {
            "logits": logits,
            "hidden_states": h,
            "pre_norm_hidden": x,
            "branch_input": branch_input,
            "cache": new_cache,
        }
        if cfg.num_experts > 0 and aux is not None:
            # token-weighted [load_balance, router_z] over all layers —
            # trainers add router_aux_coef/router_z_coef · these to the loss
            out["router_aux_loss"] = router_aux_summary(aux)
        return out

    def _pipelined_blocks(
        self, mesh, x, attention_mask, positions, use_flash, cache, cache_index, branch_layer
    ):
        """Run the stacked blocks through the GPipe schedule over the mesh's
        ``pipe`` axis (``parallel/pipeline.py``) — the reference's Megatron
        pipeline engine (``modeling_nemo_ilql.py:426-442``), here one jitted
        program with compiler-inserted stage handoffs. Attention inputs
        (bias/flash args) are rebuilt per microbatch inside each stage, since
        different stages hold different microbatches at any tick."""
        cfg = self.config
        from trlx_tpu.parallel.pipeline import pick_microbatches, pipeline_blocks

        B = x.shape[0]
        num_stages = mesh.shape["pipe"]
        num_micro = pick_microbatches(B, num_stages, cfg.pipe_microbatches)
        branch_at = cfg.num_layers - branch_layer if branch_layer is not None else -1
        body_block = Block(cfg, parent=None)
        in_decode = cache is not None and cache_index is not None

        def make_attn_inputs(mask_mb, pos_mb, ci_mb):
            # ci_mb: this stage's microbatch slice of a [B]-vector
            # cache_index (speculative decoding), or the scalar/None given
            q_offset = ci_mb if in_decode else 0
            tm = None
            if cfg.num_experts > 0:
                tm = (
                    _token_validity(mask_mb, q_offset, pos_mb.shape[1])
                    if in_decode
                    else mask_mb
                )
            return self._attn_inputs(mask_mb, pos_mb, q_offset, use_flash) + (pos_mb, tm)

        def apply_block(layer_params, h, attn_inputs, cache_layer, cidx):
            bias_mb, flash_mb, pos_mb, tm = attn_inputs
            return body_block.apply(
                {"params": layer_params}, h, bias_mb, pos_mb, cache_layer, cidx, flash_mb, tm
            )

        if cfg.remat in ("full", "minimal"):
            apply_block = jax.checkpoint(apply_block, policy=_remat_policy(cfg))

        return pipeline_blocks(
            self.variables["params"]["h_scan"]["block"],
            x,
            attention_mask.astype(jnp.int32),
            positions,
            num_stages=num_stages,
            num_microbatches=num_micro,
            make_attn_inputs=make_attn_inputs,
            apply_block=apply_block,
            cache=cache,
            cache_index=cache_index,
            branch_at=branch_at,
            mesh=mesh,
            aux_init=jnp.zeros(_ZERO_AUX, jnp.float32),
        )

    def forward_branch(
        self,
        hidden_states: jax.Array,  # [B, T, E] activations entering the branch
        branch_layer: int,
        attention_mask: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
        logits_span: Optional[Tuple[int, int]] = None,
    ) -> Dict[str, Any]:
        """Run the top ``branch_layer`` blocks + final norm + lm head.

        Applied with *frozen reference params* this replays the hydra branch
        on trunk activations shared with the policy — the reference's
        second-model-free KL baseline (``modeling_ppo.py:394-427``).
        """
        cfg = self.config
        B, T, _ = hidden_states.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, T), jnp.int32)
        if positions is None:
            positions = jnp.maximum(jnp.cumsum(attention_mask, axis=1) - 1, 0)
        bias, flash_args = self._attn_inputs(
            attention_mask,
            positions,
            0,
            cfg.resolved_attention_impl() == "pallas" and T > 1,
        )
        x = hidden_states
        if cfg.scan_layers:
            # scan over the top `branch_layer` rows of the stacked params —
            # the bound tree holds either a pre-sliced branch snapshot
            # (builder.hydra_ref_params) or the full stack
            stacked = self.variables["params"]["h_scan"]["block"]
            n_avail = jax.tree_util.tree_leaves(stacked)[0].shape[0]
            sliced = jax.tree_util.tree_map(lambda p: p[n_avail - branch_layer :], stacked)
            # parent=None: a detached functional Block (not a submodule —
            # its params come from the scanned stack, not this scope)
            body_block = Block(cfg, parent=None)

            def body(h, layer_params):
                out, _, _ = body_block.apply(
                    {"params": layer_params}, h, bias, positions,
                    flash_args=flash_args, token_mask=attention_mask,
                )
                return out, None

            if cfg.remat in ("full", "minimal"):
                body = jax.checkpoint(body, policy=_remat_policy(cfg))
            x, _ = jax.lax.scan(body, x, sliced)
        else:
            for block in self.blocks[len(self.blocks) - branch_layer :]:
                x, _, _ = block(x, bias, positions, flash_args=flash_args, token_mask=attention_mask)
        h = self.ln_f(x) if cfg.final_norm else x
        logits = self._logits(h if logits_span is None else h[:, logits_span[0] : logits_span[1]])
        return {"logits": logits, "hidden_states": h}

    def project_logits(self, hidden: jax.Array) -> jax.Array:
        """Vocab projection of (already final-normed) hidden states — lets
        loss code stream chunks through the lm head instead of
        materializing the full ``[B, T, V]`` logits (``SFTConfig.
        chunked_loss``; the [B,T,V] tensor is the peak-memory item at
        BLOOM-scale vocabularies)."""
        return self._logits(hidden)

    def init_cache(self, batch_size: int, max_length: int, dtype=None) -> List[Dict[str, jax.Array]]:
        """Allocate an all-zeros KV cache pytree."""
        return make_kv_cache(self.config, batch_size, max_length, dtype)


def make_kv_cache(
    cfg: TransformerConfig, batch_size: int, max_length: int, dtype=None
) -> Any:
    """All-zeros KV cache pytree for ``cfg`` (usable outside module ``apply``).

    Layout follows the block layout: a per-layer list of ``{"k", "v"}`` dicts,
    or one stacked dict with a leading layer dim when ``cfg.scan_layers``.
    """
    dtype = dtype or cfg.dtype
    shape = (batch_size, max_length, cfg.kv_heads, cfg.dims_per_head)
    if cfg.scan_layers:
        return {
            "k": jnp.zeros((cfg.num_layers,) + shape, dtype),
            "v": jnp.zeros((cfg.num_layers,) + shape, dtype),
        }
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(cfg.num_layers)
    ]


def stack_layer_params(backbone: Dict[str, Any], num_layers: int, prefix: str = "h_") -> Dict[str, Any]:
    """Per-layer ``h_i`` subtrees → one stacked ``h_scan/block`` subtree
    (leading layer dim). Converts HF-imported / unscanned param trees into the
    ``scan_layers`` layout."""
    out = {
        k: v
        for k, v in backbone.items()
        if not (k.startswith(prefix) and k[len(prefix) :].isdigit())
    }
    layers = [backbone[f"{prefix}{i}"] for i in range(num_layers)]
    out["h_scan"] = {"block": jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)}
    return out


def unstack_layer_params(backbone: Dict[str, Any], prefix: str = "h_") -> Dict[str, Any]:
    """Inverse of :func:`stack_layer_params` — for HF-format export and
    checkpoint interop with unscanned layouts."""
    if "h_scan" not in backbone:
        return backbone
    out = {k: v for k, v in backbone.items() if k != "h_scan"}
    stacked = backbone["h_scan"]["block"]
    num_layers = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    for i in range(num_layers):
        out[f"{prefix}{i}"] = jax.tree_util.tree_map(lambda p: p[i], stacked)
    return out


BUILTIN_SPECS = {
    "gpt2": TransformerConfig.gpt2,
    "llama": TransformerConfig.llama,
    "mistral": TransformerConfig.mistral,
    "mixtral": TransformerConfig.mixtral,
    "gptj": TransformerConfig.gptj,
    "gptneox": TransformerConfig.gptneox,
    "pythia": TransformerConfig.gptneox,
    "opt": TransformerConfig.opt,
    "bloom": TransformerConfig.bloom,
}


def config_from_spec(spec: str, **overrides) -> TransformerConfig:
    """Parse a ``builtin:<family>-<size>`` model spec into a config."""
    if spec.startswith("builtin:"):
        spec = spec.split(":", 1)[1]
    family, _, size = spec.partition("-")
    if family not in BUILTIN_SPECS:
        raise ValueError(f"Unknown model family '{family}'. Known: {sorted(BUILTIN_SPECS)}")
    return BUILTIN_SPECS[family](size or "test", **overrides)
