"""Value / Q heads and LM wrapper modules.

Reference equivalents: ``make_head`` MLP (``trlx/utils/modeling.py:25-31``),
``AutoModelForCausalLMWithValueHead`` (``trlx/models/modeling_ppo.py:250-328``),
``ILQLHeads`` (``trlx/models/modeling_ilql.py:135-193``).

Target-Q heads are plain parameter subtrees: "frozen" means masked out of the
optimizer (``trlx_tpu/utils.get_optimizer(mask=...)``), and the Polyak sync is
a jitted ``tree_map`` over two subtrees — no module surgery needed.
"""

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from trlx_tpu.models.transformer import (
    CausalTransformer,
    TransformerConfig,
    _dense,
    param_with_axes,
)


class MLPHead(nn.Module):
    """Two-layer MLP head: Linear(E→2E) → ReLU → Linear(2E→out)."""

    config: TransformerConfig
    out_features: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        h = _dense(cfg, 2 * cfg.hidden_size, True, ("embed", "mlp_head"), "in_proj")(x)
        h = nn.relu(h)
        # head outputs are tiny; compute in f32 for stable values/losses
        out = nn.Dense(
            self.out_features,
            use_bias=True,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=param_with_axes(nn.initializers.normal(0.02), ("mlp_head", "head_out")),
            bias_init=param_with_axes(nn.initializers.zeros, ("head_out",)),
            name="out_proj",
        )(h)
        return out


class CausalLMWithValueHead(nn.Module):
    """Policy LM + scalar value head on the final hidden states."""

    config: TransformerConfig

    def setup(self):
        self.backbone = CausalTransformer(self.config, name="backbone")
        self.v_head = MLPHead(self.config, 1, name="v_head")

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
        branch_layer: Optional[int] = None,
        logits_span: Optional[Tuple[int, int]] = None,
    ) -> Dict[str, Any]:
        out = self.backbone(
            input_ids,
            attention_mask=attention_mask,
            positions=positions,
            cache=cache,
            cache_index=cache_index,
            branch_layer=branch_layer,
            logits_span=logits_span,
        )
        out["value"] = self.v_head(out["hidden_states"])[..., 0]
        return out

    def forward_branch(
        self, hidden_states, branch_layer, attention_mask=None, positions=None, logits_span=None
    ):
        return self.backbone.forward_branch(
            hidden_states, branch_layer, attention_mask, positions, logits_span
        )

    def init_cache(self, batch_size, max_length, dtype=None):
        return self.backbone.init_cache(batch_size, max_length, dtype)


class ILQLHeadsModule(nn.Module):
    """V head + n Q heads + n frozen target-Q heads over hidden states."""

    config: TransformerConfig
    two_qs: bool = True

    def setup(self):
        n_qs = 2 if self.two_qs else 1
        self.v_head = MLPHead(self.config, 1, name="v_head")
        self.q_heads = [
            MLPHead(self.config, self.config.vocab_size, name=f"q_head_{i}") for i in range(n_qs)
        ]
        self.target_q_heads = [
            MLPHead(self.config, self.config.vocab_size, name=f"target_q_head_{i}")
            for i in range(n_qs)
        ]

    def __call__(self, hs: jax.Array) -> Tuple[Tuple[jax.Array, ...], Tuple[jax.Array, ...], jax.Array]:
        return self.heads_on(hs, hs)

    def heads_on(self, hs_actions: jax.Array, hs_states: jax.Array):
        """Q/target-Q heads on action positions, V head on state positions."""
        qs = tuple(q(hs_actions) for q in self.q_heads)
        target_qs = tuple(
            jax.lax.stop_gradient(q(hs_actions)) for q in self.target_q_heads
        )
        vs = self.v_head(hs_states)
        return qs, target_qs, vs


class CausalLMWithILQLHeads(nn.Module):
    """Policy LM + ILQL heads (V, twin Q, twin target-Q)."""

    config: TransformerConfig
    two_qs: bool = True

    def setup(self):
        self.backbone = CausalTransformer(self.config, name="backbone")
        self.ilql_heads = ILQLHeadsModule(self.config, self.two_qs, name="ilql_heads")

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        positions: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
        logits_span: Optional[Tuple[int, int]] = None,
    ) -> Dict[str, Any]:
        out = self.backbone(
            input_ids, attention_mask=attention_mask, positions=positions,
            cache=cache, cache_index=cache_index, logits_span=logits_span,
        )
        # the vocab-sized Q heads are as expensive as the lm head — restrict
        # them to the same span (V stays full: values are per-state scalars)
        hs = out["hidden_states"]
        hs_q = hs if logits_span is None else hs[:, logits_span[0] : logits_span[1]]
        qs, target_qs, vs = self.ilql_heads.heads_on(hs_q, hs)
        out.update(qs=qs, target_qs=target_qs, vs=vs)
        return out

    def init_cache(self, batch_size, max_length, dtype=None):
        return self.backbone.init_cache(batch_size, max_length, dtype)

    def backbone_forward(
        self, input_ids, attention_mask=None, positions=None, cache=None,
        cache_index=None, logits_span=None,
    ):
        """Backbone-only forward (no heads) — the training loss gathers
        hidden states at action/state indices first and applies heads to the
        gathered positions only (the reference's ``ILQLHeads.forward``
        index-select, ``trlx/models/modeling_ilql.py:160-180``)."""
        return self.backbone(
            input_ids,
            attention_mask=attention_mask,
            positions=positions,
            cache=cache,
            cache_index=cache_index,
            logits_span=logits_span,
        )

    def project_logits(self, hidden):
        """Vocab projection of gathered hidden states — the loss projects
        only the action positions instead of the full sequence, so the
        ``[B, T, V]`` logits tensor is never materialized."""
        return self.backbone.project_logits(hidden)

    def heads_on(self, hs_actions, hs_states):
        """Apply Q/target-Q heads at action positions, V head at states."""
        return self.ilql_heads.heads_on(hs_actions, hs_states)


def sync_target_q_params(params: Dict[str, Any], alpha: float) -> Dict[str, Any]:
    """Polyak update: target ← α·q + (1−α)·target.

    ``params`` is the full model param tree containing ``ilql_heads`` with
    ``q_head_i`` / ``target_q_head_i`` subtrees (reference semantics:
    ``modeling_ilql.py:182-193``).
    """
    heads = params["ilql_heads"]
    new_heads = dict(heads)
    for name in heads:
        if name.startswith("q_head_"):
            target_name = "target_" + name
            new_heads[target_name] = jax.tree_util.tree_map(
                lambda q, t: alpha * q + (1.0 - alpha) * t,
                heads[name],
                heads[target_name],
            )
    out = dict(params)
    out["ilql_heads"] = new_heads
    return out


# ---------------------------------------------------------------------------
# seq2seq (T5) wrappers — reference ``AutoModelForSeq2SeqLMWith(Hydra)ValueHead``
# (``trlx/models/modeling_ppo.py:948-1110``) and
# ``AutoModelForSeq2SeqLMWithILQLHeads`` (``modeling_ilql.py:347-488``).
# Heads attach to *decoder* hidden states.
# ---------------------------------------------------------------------------


class Seq2SeqLMWithValueHead(nn.Module):
    """T5 policy + scalar value head on decoder hidden states."""

    config: Any  # Seq2SeqConfig

    def setup(self):
        from trlx_tpu.models.seq2seq import T5Transformer

        self.backbone = T5Transformer(self.config, name="backbone")
        self.v_head = MLPHead(self.config, 1, name="v_head")

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        decoder_input_ids: Optional[jax.Array] = None,
        decoder_attention_mask: Optional[jax.Array] = None,
        branch_layer: Optional[int] = None,
    ) -> Dict[str, Any]:
        out = self.backbone(
            input_ids,
            attention_mask=attention_mask,
            decoder_input_ids=decoder_input_ids,
            decoder_attention_mask=decoder_attention_mask,
            branch_layer=branch_layer,
        )
        out["value"] = self.v_head(out["hidden_states"])[..., 0]
        return out

    def encode_for_decode(self, input_ids, attention_mask, max_decode_len):
        return self.backbone.encode_for_decode(input_ids, attention_mask, max_decode_len)

    def decode(self, decoder_input_ids, encoder_hidden, encoder_mask, cache=None, cache_index=None):
        out = self.backbone.decode(
            decoder_input_ids, encoder_hidden, encoder_mask, cache=cache, cache_index=cache_index
        )
        out["value"] = self.v_head(out["hidden_states"])[..., 0]
        return out

    def forward_branch(
        self, hidden_states, branch_layer, encoder_hidden, encoder_mask, decoder_mask=None
    ):
        return self.backbone.forward_branch(
            hidden_states, branch_layer, encoder_hidden, encoder_mask, decoder_mask
        )


class Seq2SeqLMWithILQLHeads(nn.Module):
    """T5 policy + ILQL heads (V, twin Q, twin target-Q) on decoder hiddens."""

    config: Any  # Seq2SeqConfig
    two_qs: bool = True

    def setup(self):
        from trlx_tpu.models.seq2seq import T5Transformer

        self.backbone = T5Transformer(self.config, name="backbone")
        self.ilql_heads = ILQLHeadsModule(self.config, self.two_qs, name="ilql_heads")

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        decoder_input_ids: Optional[jax.Array] = None,
        decoder_attention_mask: Optional[jax.Array] = None,
    ) -> Dict[str, Any]:
        out = self.backbone(
            input_ids,
            attention_mask=attention_mask,
            decoder_input_ids=decoder_input_ids,
            decoder_attention_mask=decoder_attention_mask,
        )
        qs, target_qs, vs = self.ilql_heads(out["hidden_states"])
        out.update(qs=qs, target_qs=target_qs, vs=vs)
        return out

    def backbone_forward(
        self,
        input_ids,
        attention_mask=None,
        decoder_input_ids=None,
        decoder_attention_mask=None,
        logits_span=None,
    ):
        return self.backbone(
            input_ids,
            attention_mask=attention_mask,
            decoder_input_ids=decoder_input_ids,
            decoder_attention_mask=decoder_attention_mask,
            logits_span=logits_span,
        )

    def project_logits(self, hidden):
        """Vocab projection of gathered decoder hidden states (the ILQL loss
        projects action positions only — see the causal twin)."""
        return self.backbone.project_logits(hidden)

    def heads_on(self, hs_actions, hs_states):
        return self.ilql_heads.heads_on(hs_actions, hs_states)

    def encode_for_decode(self, input_ids, attention_mask, max_decode_len):
        return self.backbone.encode_for_decode(input_ids, attention_mask, max_decode_len)

    def decode(self, decoder_input_ids, encoder_hidden, encoder_mask, cache=None, cache_index=None):
        out = self.backbone.decode(
            decoder_input_ids, encoder_hidden, encoder_mask, cache=cache, cache_index=cache_index
        )
        qs, target_qs, vs = self.ilql_heads(out["hidden_states"])
        out.update(qs=qs, target_qs=target_qs, vs=vs)
        return out
