"""Model assembly: ModelConfig → (flax module, params).

The reference's ``get_arch`` + ``PreTrainedModelWrapper.from_pretrained``
(``trlx/trainer/accelerate_ppo_trainer.py:120-134``,
``trlx/models/modeling_base.py:53-141``) equivalent: resolves a model spec
(``builtin:<family>-<size>`` or a local HF checkpoint path), builds the
appropriate wrapper module (plain / value-head / ILQL-heads), initializes or
imports weights, and reports which params the hydra reference branch needs.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.configs import ModelConfig, ParallelConfig
from trlx_tpu.models.heads import CausalLMWithILQLHeads, CausalLMWithValueHead
from trlx_tpu.models.transformer import (
    CausalTransformer,
    TransformerConfig,
    config_from_spec,
)

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def resolve_transformer_config(
    model_config: ModelConfig, parallel: Optional[ParallelConfig] = None
) -> Tuple[TransformerConfig, Optional[str]]:
    """Resolve (TransformerConfig, hf_path or None) from a ModelConfig."""
    import dataclasses

    path = model_config.model_path
    overrides: Dict[str, Any] = dict(model_config.model_extra_kwargs or {})
    if parallel is not None:
        overrides.setdefault("param_dtype", DTYPES[parallel.param_dtype])
        overrides.setdefault("dtype", DTYPES[parallel.compute_dtype])
        overrides.setdefault("remat", parallel.remat)
        overrides.setdefault("scan_layers", parallel.scan_layers)

    if path.startswith("builtin:"):
        return config_from_spec(path, **overrides), None

    from trlx_tpu.models.hf_interop import config_from_hf
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(path))
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg, path


def build_causal_lm(
    model_config: ModelConfig,
    parallel: Optional[ParallelConfig] = None,
    head: Optional[str] = None,  # None | "value" | "ilql"
    two_qs: bool = True,
    seed: int = 0,
) -> Tuple[Any, Dict[str, Any], TransformerConfig]:
    """Build module + params. Pretrained weights (HF torch) replace the
    backbone subtree; heads stay freshly initialized."""
    tcfg, hf_path = resolve_transformer_config(model_config, parallel)

    if head == "value":
        module = CausalLMWithValueHead(tcfg)
    elif head == "ilql":
        module = CausalLMWithILQLHeads(tcfg, two_qs=two_qs)
    else:
        module = CausalTransformer(tcfg)

    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, 8), jnp.int32)
    params = module.init(rng, dummy)["params"]

    if head == "ilql":
        # target-Q heads start as exact copies of the Q heads (reference
        # deepcopies them at init, modeling_ilql.py:154) — training toward
        # fresh random targets would be noise until many Polyak syncs.
        from trlx_tpu.models.heads import sync_target_q_params

        params = sync_target_q_params(params, alpha=1.0)

    if hf_path is not None:
        from trlx_tpu.models.hf_interop import load_pretrained

        hf_params, _ = load_pretrained(hf_path)
        backbone = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, tcfg.param_dtype), hf_params["backbone"]
        )
        if head is None:
            params = backbone
        else:
            params = dict(params)
            params["backbone"] = backbone
    return module, params, tcfg


def hydra_ref_params(params: Dict[str, Any], tcfg: TransformerConfig, num_layers_unfrozen: int) -> Dict[str, Any]:
    """Extract the frozen reference branch: top ``num_layers_unfrozen`` blocks
    + final norm + lm head (+ tied embedding). A small pytree snapshot taken
    at setup — the GSPMD analogue of the reference's deepcopy'd hydra heads
    (``modeling_ppo.py:331-391``)."""
    backbone = params["backbone"] if "backbone" in params else params
    keep = {}
    start = tcfg.num_layers - num_layers_unfrozen
    for i in range(start, tcfg.num_layers):
        keep[f"h_{i}"] = backbone[f"h_{i}"]
    if tcfg.final_norm:
        keep["ln_f"] = backbone["ln_f"]
    if tcfg.tie_word_embeddings:
        keep["wte"] = backbone["wte"]
    else:
        keep["lm_head"] = backbone["lm_head"]
    return jax.tree_util.tree_map(lambda x: x, keep)  # shallow copy


def trainable_mask(
    params: Dict[str, Any], tcfg: TransformerConfig, num_layers_unfrozen: int
) -> Dict[str, Any]:
    """Bool pytree: True for trainable leaves. ``num_layers_unfrozen == -1``
    trains everything; otherwise only the top-k blocks, final norm, lm head,
    and any value/Q heads train (reference ``freeze_bottom_causal_layers``,
    ``trlx/utils/modeling.py:34-44``). Target-Q heads never train."""

    def mark(tree, value: bool):
        return jax.tree_util.tree_map(lambda _: value, tree)

    mask: Dict[str, Any] = {}
    for top_key, subtree in params.items():
        if top_key == "backbone":
            sub = {}
            for name, layer_tree in subtree.items():
                if num_layers_unfrozen < 0:
                    trainable = True
                elif name.startswith("h_"):
                    # only bottom blocks freeze; embeddings/norm/head stay
                    # trainable (reference freeze_bottom_causal_layers,
                    # trlx/utils/modeling.py:34-44)
                    trainable = int(name[2:]) >= tcfg.num_layers - num_layers_unfrozen
                else:
                    trainable = True
                sub[name] = mark(layer_tree, trainable)
            mask[top_key] = sub
        elif top_key == "ilql_heads":
            mask[top_key] = {
                name: mark(tree, not name.startswith("target_q_head"))
                for name, tree in subtree.items()
            }
        else:
            mask[top_key] = mark(subtree, True)
    return mask


# ---------------------------------------------------------------------------
# seq2seq (T5) assembly — reference seq2seq arch selection
# (``trlx/trainer/accelerate_ppo_trainer.py:120-134`` picks the Seq2Seq
# wrappers when ``config.model.model_arch_type == "seq2seq"``).
# ---------------------------------------------------------------------------


def resolve_seq2seq_config(
    model_config: ModelConfig, parallel: Optional[ParallelConfig] = None
):
    """Resolve (Seq2SeqConfig, hf_path or None) from a ModelConfig."""
    import dataclasses

    from trlx_tpu.models.seq2seq import Seq2SeqConfig

    path = model_config.model_path
    overrides: Dict[str, Any] = dict(model_config.model_extra_kwargs or {})
    overrides.pop("scan_layers", None)
    if parallel is not None:
        overrides.setdefault("param_dtype", DTYPES[parallel.param_dtype])
        overrides.setdefault("dtype", DTYPES[parallel.compute_dtype])
        overrides.setdefault("remat", parallel.remat)

    if path.startswith("builtin:"):
        spec = path.split(":", 1)[1]
        family, _, size = spec.partition("-")
        makers = {"t5": Seq2SeqConfig.t5, "flan_t5": Seq2SeqConfig.flan_t5}
        if family not in makers:
            raise ValueError(f"Unknown seq2seq family '{family}'. Known: {sorted(makers)}")
        return makers[family](size or "test", **overrides), None

    from transformers import AutoConfig

    from trlx_tpu.models.hf_interop import seq2seq_config_from_hf

    cfg = seq2seq_config_from_hf(AutoConfig.from_pretrained(path))
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg, path


def build_seq2seq_lm(
    model_config: ModelConfig,
    parallel: Optional[ParallelConfig] = None,
    head: Optional[str] = None,  # None | "value" | "ilql"
    two_qs: bool = True,
    seed: int = 0,
):
    """Build seq2seq module + params (pretrained backbone import, fresh heads)."""
    from trlx_tpu.models.heads import Seq2SeqLMWithILQLHeads, Seq2SeqLMWithValueHead
    from trlx_tpu.models.seq2seq import T5Transformer

    scfg, hf_path = resolve_seq2seq_config(model_config, parallel)

    if head == "value":
        module = Seq2SeqLMWithValueHead(scfg)
    elif head == "ilql":
        module = Seq2SeqLMWithILQLHeads(scfg, two_qs=two_qs)
    else:
        module = T5Transformer(scfg)

    rng = jax.random.PRNGKey(seed)
    enc = jnp.zeros((1, 8), jnp.int32)
    dec = jnp.zeros((1, 4), jnp.int32)
    params = module.init(rng, enc, decoder_input_ids=dec)["params"]

    if head == "ilql":
        from trlx_tpu.models.heads import sync_target_q_params

        params = sync_target_q_params(params, alpha=1.0)

    if hf_path is not None:
        from trlx_tpu.models.hf_interop import load_pretrained_seq2seq

        hf_params, _ = load_pretrained_seq2seq(hf_path)
        backbone = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, scfg.param_dtype), hf_params["backbone"]
        )
        if head is None:
            params = backbone
        else:
            params = dict(params)
            params["backbone"] = backbone
    return module, params, scfg


def seq2seq_hydra_ref_params(
    params: Dict[str, Any], scfg, num_layers_unfrozen: int
) -> Dict[str, Any]:
    """Frozen seq2seq reference branch: top ``num_layers_unfrozen`` *decoder*
    blocks + decoder final norm + rel-pos bias table + lm head/tied embedding
    (reference ``T5Branch``, ``modeling_ppo.py:1113-1222``)."""
    backbone = params["backbone"] if "backbone" in params else params
    keep = {}
    start = scfg.num_decoder_layers - num_layers_unfrozen
    for i in range(start, scfg.num_decoder_layers):
        keep[f"dec_{i}"] = backbone[f"dec_{i}"]
    keep["dec_ln_f"] = backbone["dec_ln_f"]
    keep["dec_rel_bias"] = backbone["dec_rel_bias"]
    if scfg.tie_word_embeddings:
        keep["wte"] = backbone["wte"]
    else:
        keep["lm_head"] = backbone["lm_head"]
    return jax.tree_util.tree_map(lambda x: x, keep)


def seq2seq_trainable_mask(
    params: Dict[str, Any], scfg, num_layers_unfrozen: int
) -> Dict[str, Any]:
    """Bool pytree for seq2seq freezing. Mirrors the reference's
    ``freeze_bottom_seq2seq_layers`` (``trlx/utils/modeling.py:47-66``):
    with k>0 unfrozen, the shared embedding, the whole encoder, both final
    norms, and all but the top-k decoder blocks freeze; the lm head and any
    value/Q heads stay trainable. At k=0 the reference freezes everything
    *except* the decoder blocks (``decoder.block[:-0] == []``), so the whole
    decoder trains — mirrored here for behavioral parity."""

    def mark(tree, value: bool):
        return jax.tree_util.tree_map(lambda _: value, tree)

    frozen_names = {"wte", "enc_ln_f", "dec_ln_f", "enc_rel_bias", "dec_rel_bias"}
    mask: Dict[str, Any] = {}
    for top_key, subtree in params.items():
        if top_key == "backbone":
            sub = {}
            for name, layer_tree in subtree.items():
                if num_layers_unfrozen < 0:
                    trainable = True
                elif name.startswith("enc_") or name in frozen_names:
                    trainable = False
                elif name.startswith("dec_") and name[4:].isdigit():
                    trainable = (
                        num_layers_unfrozen == 0  # reference: k=0 trains all decoder blocks
                        or int(name[4:]) >= scfg.num_decoder_layers - num_layers_unfrozen
                    )
                else:
                    trainable = True  # lm_head
                sub[name] = mark(layer_tree, trainable)
            mask[top_key] = sub
        elif top_key == "ilql_heads":
            mask[top_key] = {
                name: mark(tree, not name.startswith("target_q_head"))
                for name, tree in subtree.items()
            }
        else:
            mask[top_key] = mark(subtree, True)
    return mask
