"""Model assembly: ModelConfig → (flax module, params).

The reference's ``get_arch`` + ``PreTrainedModelWrapper.from_pretrained``
(``trlx/trainer/accelerate_ppo_trainer.py:120-134``,
``trlx/models/modeling_base.py:53-141``) equivalent: resolves a model spec
(``builtin:<family>-<size>`` or a local HF checkpoint path), builds the
appropriate wrapper module (plain / value-head / ILQL-heads), initializes or
imports weights, and reports which params the hydra reference branch needs.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.configs import ModelConfig, ParallelConfig
from trlx_tpu.models.heads import CausalLMWithILQLHeads, CausalLMWithValueHead
from trlx_tpu.models.transformer import (
    CausalTransformer,
    TransformerConfig,
    config_from_spec,
)

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


LORA_TARGET_GROUPS = {
    "attention": ("q_proj", "k_proj", "v_proj", "o_proj"),
    "mlp": ("gate_proj", "up_proj", "down_proj"),
}
LORA_TARGET_GROUPS["all"] = LORA_TARGET_GROUPS["attention"] + LORA_TARGET_GROUPS["mlp"]


def parse_peft_overrides(peft_kwargs: Dict[str, Any]) -> Dict[str, Any]:
    """ModelConfig.peft_kwargs → backbone config overrides (reference
    ``parse_delta_kwargs``, ``trlx/utils/modeling.py:419-450``; like the
    reference, only LoRA is supported)."""
    kw = dict(peft_kwargs)
    peft_type = str(kw.pop("peft_type", kw.pop("delta_type", "lora"))).lower()
    if peft_type != "lora":
        raise ValueError(f"Only LoRA peft is supported (got '{peft_type}')")
    modified = kw.pop("modified_modules", "all")
    if isinstance(modified, str):
        if modified not in LORA_TARGET_GROUPS:
            raise ValueError(
                f"modified_modules '{modified}' not in {sorted(LORA_TARGET_GROUPS)}; "
                "pass an explicit list of projection names instead"
            )
        targets = LORA_TARGET_GROUPS[modified]
    else:
        targets = tuple(modified)
    out = dict(
        lora_r=int(kw.pop("r", kw.pop("lora_r", 8))),
        lora_alpha=float(kw.pop("lora_alpha", 16.0)),
        lora_targets=targets,
    )
    if kw:
        raise ValueError(f"Unknown peft_kwargs keys: {sorted(kw)}")
    return out


def merge_trees(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Deep-merge ``override`` into ``base`` (override wins on leaves). Used to
    overlay imported HF weights onto an initialized tree without dropping
    params the checkpoint does not carry (LoRA adapters, fresh heads)."""
    out = dict(base)
    for k, v in override.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_trees(out[k], v)
        else:
            out[k] = v
    return out


def merge_lora_params(params: Dict[str, Any], cfg) -> Dict[str, Any]:
    """Fold trained adapters into their kernels (``W += (alpha/r)·AB``) and
    drop the lora leaves — for HF-format export of a LoRA-tuned model."""
    import numpy as np

    if not getattr(cfg, "lora_r", 0):
        return params  # nothing to fold
    scale = cfg.lora_alpha / cfg.lora_r

    def fold(tree):
        if not isinstance(tree, dict):
            return tree
        if "lora_a" in tree and "kernel" in tree:
            out = {k: v for k, v in tree.items() if k not in ("lora_a", "lora_b")}
            out["kernel"] = tree["kernel"] + (
                np.asarray(tree["lora_a"]) @ np.asarray(tree["lora_b"])
            ) * scale
            return out
        return {k: fold(v) for k, v in tree.items()}

    return fold(params)



def _assemble_overrides(
    model_config: ModelConfig,
    parallel: Optional[ParallelConfig],
    scan_layers_supported: bool = True,
) -> Dict[str, Any]:
    """Shared config-override assembly for both architectures: user extras,
    peft translation, and parallel-derived dtypes/remat."""
    overrides: Dict[str, Any] = dict(model_config.model_extra_kwargs or {})
    if not scan_layers_supported:
        overrides.pop("scan_layers", None)
    if model_config.peft_kwargs:
        overrides.update(parse_peft_overrides(model_config.peft_kwargs))
    if parallel is not None:
        overrides.setdefault("param_dtype", DTYPES[parallel.param_dtype])
        overrides.setdefault("dtype", DTYPES[parallel.compute_dtype])
        overrides.setdefault("remat", parallel.remat)
        if scan_layers_supported:
            overrides.setdefault("scan_layers", parallel.scan_layers or parallel.pipe > 1)
            overrides.setdefault("pipe_microbatches", parallel.pipe_microbatches)
    return overrides


def _import_hf_backbone(params, head, backbone_numpy, param_dtype):
    """Overlay imported HF weights onto initialized params (deep merge keeps
    LoRA adapters and fresh heads)."""
    backbone = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, param_dtype), backbone_numpy
    )
    if head is None:
        return merge_trees(params, backbone)
    params = dict(params)
    params["backbone"] = merge_trees(params["backbone"], backbone)
    return params


def resolve_transformer_config(
    model_config: ModelConfig, parallel: Optional[ParallelConfig] = None
) -> Tuple[TransformerConfig, Optional[str]]:
    """Resolve (TransformerConfig, hf_path or None) from a ModelConfig."""
    import dataclasses

    path = model_config.model_path
    overrides = _assemble_overrides(model_config, parallel)

    if path.startswith("builtin:"):
        return config_from_spec(path, **overrides), None

    from trlx_tpu.models.hf_interop import config_from_hf
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(path))
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg, path


def build_causal_lm(
    model_config: ModelConfig,
    parallel: Optional[ParallelConfig] = None,
    head: Optional[str] = None,  # None | "value" | "ilql"
    two_qs: bool = True,
    seed: int = 0,
    abstract: bool = False,
) -> Tuple[Any, Dict[str, Any], TransformerConfig]:
    """Build module + params. Pretrained weights (HF torch) replace the
    backbone subtree; heads stay freshly initialized.

    ``abstract=True`` returns a ``ShapeDtypeStruct`` pytree instead of real
    arrays (and skips any pretrained-weight load): enough to lower/compile
    the training programs for cost/memory analysis without materializing a
    multi-GB model (``trlx_tpu/perf.py``)."""
    tcfg, hf_path = resolve_transformer_config(model_config, parallel)

    if head == "value":
        module = CausalLMWithValueHead(tcfg)
    elif head == "ilql":
        module = CausalLMWithILQLHeads(tcfg, two_qs=two_qs)
    else:
        module = CausalTransformer(tcfg)

    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, 8), jnp.int32)

    def make_params():
        p = module.init(rng, dummy)["params"]
        if head == "ilql":
            # target-Q heads start as exact copies of the Q heads (reference
            # deepcopies them at init, modeling_ilql.py:154) — training toward
            # fresh random targets would be noise until many Polyak syncs.
            from trlx_tpu.models.heads import sync_target_q_params

            p = sync_target_q_params(p, alpha=1.0)
        return p

    if abstract:
        return module, jax.eval_shape(make_params), tcfg

    params = make_params()

    if hf_path is not None:
        from trlx_tpu.models.hf_interop import load_pretrained

        hf_params, _ = load_pretrained(hf_path)
        backbone = hf_params["backbone"]
        if tcfg.scan_layers:
            from trlx_tpu.models.transformer import stack_layer_params

            backbone = stack_layer_params(backbone, tcfg.num_layers)
        params = _import_hf_backbone(params, head, backbone, tcfg.param_dtype)
    return module, params, tcfg


def hydra_ref_params(params: Dict[str, Any], tcfg: TransformerConfig, num_layers_unfrozen: int) -> Dict[str, Any]:
    """Extract the frozen reference branch: top ``num_layers_unfrozen`` blocks
    + final norm + lm head (+ tied embedding). A small pytree snapshot taken
    at setup — the GSPMD analogue of the reference's deepcopy'd hydra heads
    (``modeling_ppo.py:331-391``)."""
    backbone = params["backbone"] if "backbone" in params else params
    keep = {}
    start = tcfg.num_layers - num_layers_unfrozen
    if tcfg.scan_layers:
        keep["h_scan"] = {
            "block": jax.tree_util.tree_map(
                lambda p: p[start:], backbone["h_scan"]["block"]
            )
        }
    else:
        for i in range(start, tcfg.num_layers):
            keep[f"h_{i}"] = backbone[f"h_{i}"]
    if tcfg.final_norm:
        keep["ln_f"] = backbone["ln_f"]
    if tcfg.tie_word_embeddings:
        keep["wte"] = backbone["wte"]
    else:
        keep["lm_head"] = backbone["lm_head"]
    return jax.tree_util.tree_map(lambda x: x, keep)  # shallow copy




def _mark(tree, value: bool):
    return jax.tree_util.tree_map(lambda _: value, tree)


def _mark_lora(tree, layer_in_range: bool):
    """True only on adapter leaves (``lora_*``) when the layer is in the
    unfrozen range — the base always freezes under LoRA."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: layer_in_range
        and str(getattr(path[-1], "key", "")).startswith("lora_"),
        tree,
    )


def _mask_heads(subtree):
    return {
        name: _mark(tree, not name.startswith("target_q_head"))
        for name, tree in subtree.items()
    }


def _scan_layer_vector(tcfg, num_layers_unfrozen: int):
    """Per-layer 0/1 trainability over the stacked layer dim, or None when
    every layer trains (``num_layers_unfrozen == -1``)."""
    import numpy as np

    if num_layers_unfrozen < 0:
        return None
    vec = np.zeros(tcfg.num_layers, np.float32)
    if num_layers_unfrozen > 0:
        vec[tcfg.num_layers - num_layers_unfrozen :] = 1.0
    return vec


def _mask_scan_blocks(layer_tree, tcfg, num_layers_unfrozen: int, lora: bool):
    """Mask leaves for the stacked ``h_scan`` subtree: bools where uniform,
    a per-layer 0/1 vector where only some layers train (consumed by
    ``get_optimizer``'s layer-wise freeze)."""
    vec = _scan_layer_vector(tcfg, num_layers_unfrozen)
    if lora:

        def leaf_mask(path, _):
            if not str(getattr(path[-1], "key", "")).startswith("lora_"):
                return False
            return True if vec is None else vec

        return jax.tree_util.tree_map_with_path(leaf_mask, layer_tree)
    if vec is None or vec.all():
        return _mark(layer_tree, True)
    if not vec.any():
        return _mark(layer_tree, False)
    return jax.tree_util.tree_map(lambda _: vec, layer_tree)


def trainable_mask(
    params: Dict[str, Any], tcfg: TransformerConfig, num_layers_unfrozen: int
) -> Dict[str, Any]:
    """Bool pytree: True for trainable leaves. ``num_layers_unfrozen == -1``
    trains everything; otherwise only the top-k blocks, final norm, lm head,
    and any value/Q heads train (reference ``freeze_bottom_causal_layers``,
    ``trlx/utils/modeling.py:34-44``). Target-Q heads never train.

    With LoRA enabled (``tcfg.lora_r > 0``) the base model freezes entirely
    and only adapter leaves in the unfrozen-layer range plus heads train
    (reference: OpenDelta freezes the base and trains layer-ranged
    modified_modules, ``trlx/utils/modeling.py:389-417``)."""

    lora = getattr(tcfg, "lora_r", 0) > 0
    mask: Dict[str, Any] = {}
    for top_key, subtree in params.items():
        if top_key == "backbone":
            sub = {}
            for name, layer_tree in subtree.items():
                if name == "h_scan":
                    sub[name] = _mask_scan_blocks(
                        layer_tree, tcfg, num_layers_unfrozen, lora
                    )
                    continue
                if name.startswith("h_"):
                    in_range = (
                        num_layers_unfrozen < 0
                        or int(name[2:]) >= tcfg.num_layers - num_layers_unfrozen
                    )
                else:
                    in_range = True
                if lora:
                    sub[name] = _mark_lora(layer_tree, in_range and name.startswith("h_"))
                else:
                    sub[name] = _mark(layer_tree, in_range)
            mask[top_key] = sub
        elif top_key == "ilql_heads":
            mask[top_key] = _mask_heads(subtree)
        else:
            mask[top_key] = _mark(subtree, True)
    return mask


# ---------------------------------------------------------------------------
# seq2seq (T5) assembly — reference seq2seq arch selection
# (``trlx/trainer/accelerate_ppo_trainer.py:120-134`` picks the Seq2Seq
# wrappers when ``config.model.model_arch_type == "seq2seq"``).
# ---------------------------------------------------------------------------


def resolve_seq2seq_config(
    model_config: ModelConfig, parallel: Optional[ParallelConfig] = None
):
    """Resolve (Seq2SeqConfig, hf_path or None) from a ModelConfig."""
    import dataclasses

    from trlx_tpu.models.seq2seq import Seq2SeqConfig

    if parallel is not None and parallel.pipe > 1:
        raise ValueError(
            "pipeline parallelism (parallel.pipe > 1) is not supported for "
            "seq2seq models — the pipe schedule runs over the causal "
            "scan_layers block stack; use fsdp/model/data axes for T5"
        )
    path = model_config.model_path
    overrides = _assemble_overrides(model_config, parallel, scan_layers_supported=False)

    if path.startswith("builtin:"):
        spec = path.split(":", 1)[1]
        family, _, size = spec.partition("-")
        makers = {"t5": Seq2SeqConfig.t5, "flan_t5": Seq2SeqConfig.flan_t5}
        if family not in makers:
            raise ValueError(f"Unknown seq2seq family '{family}'. Known: {sorted(makers)}")
        return makers[family](size or "test", **overrides), None

    from transformers import AutoConfig

    from trlx_tpu.models.hf_interop import seq2seq_config_from_hf

    cfg = seq2seq_config_from_hf(AutoConfig.from_pretrained(path))
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg, path


def build_seq2seq_lm(
    model_config: ModelConfig,
    parallel: Optional[ParallelConfig] = None,
    head: Optional[str] = None,  # None | "value" | "ilql"
    two_qs: bool = True,
    seed: int = 0,
    abstract: bool = False,
):
    """Build seq2seq module + params (pretrained backbone import, fresh heads).

    ``abstract=True`` mirrors :func:`build_causal_lm`: a ShapeDtypeStruct
    pytree for lowering/compiling programs without materializing weights."""
    from trlx_tpu.models.heads import Seq2SeqLMWithILQLHeads, Seq2SeqLMWithValueHead
    from trlx_tpu.models.seq2seq import T5Transformer

    scfg, hf_path = resolve_seq2seq_config(model_config, parallel)

    if head == "value":
        module = Seq2SeqLMWithValueHead(scfg)
    elif head == "ilql":
        module = Seq2SeqLMWithILQLHeads(scfg, two_qs=two_qs)
    else:
        module = T5Transformer(scfg)

    rng = jax.random.PRNGKey(seed)
    enc = jnp.zeros((1, 8), jnp.int32)
    dec = jnp.zeros((1, 4), jnp.int32)

    def make_params():
        p = module.init(rng, enc, decoder_input_ids=dec)["params"]
        if head == "ilql":
            from trlx_tpu.models.heads import sync_target_q_params

            p = sync_target_q_params(p, alpha=1.0)
        return p

    if abstract:
        return module, jax.eval_shape(make_params), scfg

    params = make_params()

    if hf_path is not None:
        from trlx_tpu.models.hf_interop import load_pretrained_seq2seq

        hf_params, _ = load_pretrained_seq2seq(hf_path)
        params = _import_hf_backbone(params, head, hf_params["backbone"], scfg.param_dtype)
    return module, params, scfg


def seq2seq_hydra_ref_params(
    params: Dict[str, Any], scfg, num_layers_unfrozen: int
) -> Dict[str, Any]:
    """Frozen seq2seq reference branch: top ``num_layers_unfrozen`` *decoder*
    blocks + decoder final norm + rel-pos bias table + lm head/tied embedding
    (reference ``T5Branch``, ``modeling_ppo.py:1113-1222``)."""
    backbone = params["backbone"] if "backbone" in params else params
    keep = {}
    start = scfg.num_decoder_layers - num_layers_unfrozen
    for i in range(start, scfg.num_decoder_layers):
        keep[f"dec_{i}"] = backbone[f"dec_{i}"]
    keep["dec_ln_f"] = backbone["dec_ln_f"]
    keep["dec_rel_bias"] = backbone["dec_rel_bias"]
    if scfg.tie_word_embeddings:
        keep["wte"] = backbone["wte"]
    else:
        keep["lm_head"] = backbone["lm_head"]
    return jax.tree_util.tree_map(lambda x: x, keep)


def seq2seq_trainable_mask(
    params: Dict[str, Any], scfg, num_layers_unfrozen: int
) -> Dict[str, Any]:
    """Bool pytree for seq2seq freezing. Mirrors the reference's
    ``freeze_bottom_seq2seq_layers`` (``trlx/utils/modeling.py:47-66``):
    with k>0 unfrozen, the shared embedding, the whole encoder, both final
    norms, and all but the top-k decoder blocks freeze; the lm head and any
    value/Q heads stay trainable. At k=0 the reference freezes everything
    *except* the decoder blocks (``decoder.block[:-0] == []``), so the whole
    decoder trains — mirrored here for behavioral parity."""

    frozen_names = {"wte", "enc_ln_f", "dec_ln_f", "enc_rel_bias", "dec_rel_bias"}
    lora = getattr(scfg, "lora_r", 0) > 0
    mask: Dict[str, Any] = {}
    for top_key, subtree in params.items():
        if top_key == "backbone":
            sub = {}
            for name, layer_tree in subtree.items():
                is_dec_block = name.startswith("dec_") and name[4:].isdigit()
                if num_layers_unfrozen < 0:
                    trainable = True
                elif name.startswith("enc_") or name in frozen_names:
                    trainable = False
                elif is_dec_block:
                    trainable = (
                        num_layers_unfrozen == 0  # reference: k=0 trains all decoder blocks
                        or int(name[4:]) >= scfg.num_decoder_layers - num_layers_unfrozen
                    )
                else:
                    trainable = True  # lm_head
                if lora:
                    # adapters only, within the unfrozen decoder range
                    # (reference hardcodes the decoder prefix for T5,
                    # trlx/utils/modeling.py:400-402)
                    sub[name] = _mark_lora(layer_tree, trainable and is_dec_block)
                else:
                    sub[name] = _mark(layer_tree, trainable)
            mask[top_key] = sub
        elif top_key == "ilql_heads":
            mask[top_key] = _mask_heads(subtree)
        else:
            mask[top_key] = _mark(subtree, True)
    return mask
