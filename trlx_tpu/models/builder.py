"""Model assembly: ModelConfig → (flax module, params).

The reference's ``get_arch`` + ``PreTrainedModelWrapper.from_pretrained``
(``trlx/trainer/accelerate_ppo_trainer.py:120-134``,
``trlx/models/modeling_base.py:53-141``) equivalent: resolves a model spec
(``builtin:<family>-<size>`` or a local HF checkpoint path), builds the
appropriate wrapper module (plain / value-head / ILQL-heads), initializes or
imports weights, and reports which params the hydra reference branch needs.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.configs import ModelConfig, ParallelConfig
from trlx_tpu.models.heads import CausalLMWithILQLHeads, CausalLMWithValueHead
from trlx_tpu.models.transformer import (
    CausalTransformer,
    TransformerConfig,
    config_from_spec,
)

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


def resolve_transformer_config(
    model_config: ModelConfig, parallel: Optional[ParallelConfig] = None
) -> Tuple[TransformerConfig, Optional[str]]:
    """Resolve (TransformerConfig, hf_path or None) from a ModelConfig."""
    import dataclasses

    path = model_config.model_path
    overrides: Dict[str, Any] = dict(model_config.model_extra_kwargs or {})
    if parallel is not None:
        overrides.setdefault("param_dtype", DTYPES[parallel.param_dtype])
        overrides.setdefault("dtype", DTYPES[parallel.compute_dtype])
        overrides.setdefault("remat", parallel.remat)
        overrides.setdefault("scan_layers", parallel.scan_layers)

    if path.startswith("builtin:"):
        return config_from_spec(path, **overrides), None

    from trlx_tpu.models.hf_interop import config_from_hf
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(path))
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg, path


def build_causal_lm(
    model_config: ModelConfig,
    parallel: Optional[ParallelConfig] = None,
    head: Optional[str] = None,  # None | "value" | "ilql"
    two_qs: bool = True,
    seed: int = 0,
) -> Tuple[Any, Dict[str, Any], TransformerConfig]:
    """Build module + params. Pretrained weights (HF torch) replace the
    backbone subtree; heads stay freshly initialized."""
    tcfg, hf_path = resolve_transformer_config(model_config, parallel)

    if head == "value":
        module = CausalLMWithValueHead(tcfg)
    elif head == "ilql":
        module = CausalLMWithILQLHeads(tcfg, two_qs=two_qs)
    else:
        module = CausalTransformer(tcfg)

    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, 8), jnp.int32)
    params = module.init(rng, dummy)["params"]

    if head == "ilql":
        # target-Q heads start as exact copies of the Q heads (reference
        # deepcopies them at init, modeling_ilql.py:154) — training toward
        # fresh random targets would be noise until many Polyak syncs.
        from trlx_tpu.models.heads import sync_target_q_params

        params = sync_target_q_params(params, alpha=1.0)

    if hf_path is not None:
        from trlx_tpu.models.hf_interop import load_pretrained

        hf_params, _ = load_pretrained(hf_path)
        backbone = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, tcfg.param_dtype), hf_params["backbone"]
        )
        if head is None:
            params = backbone
        else:
            params = dict(params)
            params["backbone"] = backbone
    return module, params, tcfg


def hydra_ref_params(params: Dict[str, Any], tcfg: TransformerConfig, num_layers_unfrozen: int) -> Dict[str, Any]:
    """Extract the frozen reference branch: top ``num_layers_unfrozen`` blocks
    + final norm + lm head (+ tied embedding). A small pytree snapshot taken
    at setup — the GSPMD analogue of the reference's deepcopy'd hydra heads
    (``modeling_ppo.py:331-391``)."""
    backbone = params["backbone"] if "backbone" in params else params
    keep = {}
    start = tcfg.num_layers - num_layers_unfrozen
    for i in range(start, tcfg.num_layers):
        keep[f"h_{i}"] = backbone[f"h_{i}"]
    if tcfg.final_norm:
        keep["ln_f"] = backbone["ln_f"]
    if tcfg.tie_word_embeddings:
        keep["wte"] = backbone["wte"]
    else:
        keep["lm_head"] = backbone["lm_head"]
    return jax.tree_util.tree_map(lambda x: x, keep)  # shallow copy


def trainable_mask(
    params: Dict[str, Any], tcfg: TransformerConfig, num_layers_unfrozen: int
) -> Dict[str, Any]:
    """Bool pytree: True for trainable leaves. ``num_layers_unfrozen == -1``
    trains everything; otherwise only the top-k blocks, final norm, lm head,
    and any value/Q heads train (reference ``freeze_bottom_causal_layers``,
    ``trlx/utils/modeling.py:34-44``). Target-Q heads never train."""

    def mark(tree, value: bool):
        return jax.tree_util.tree_map(lambda _: value, tree)

    mask: Dict[str, Any] = {}
    for top_key, subtree in params.items():
        if top_key == "backbone":
            sub = {}
            for name, layer_tree in subtree.items():
                if num_layers_unfrozen < 0:
                    trainable = True
                elif name.startswith("h_"):
                    # only bottom blocks freeze; embeddings/norm/head stay
                    # trainable (reference freeze_bottom_causal_layers,
                    # trlx/utils/modeling.py:34-44)
                    trainable = int(name[2:]) >= tcfg.num_layers - num_layers_unfrozen
                else:
                    trainable = True
                sub[name] = mark(layer_tree, trainable)
            mask[top_key] = sub
        elif top_key == "ilql_heads":
            mask[top_key] = {
                name: mark(tree, not name.startswith("target_q_head"))
                for name, tree in subtree.items()
            }
        else:
            mask[top_key] = mark(subtree, True)
    return mask
