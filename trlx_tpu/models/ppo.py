"""PPO method: KL controllers, GAE, and the clipped PPO objective — pure JAX.

Behavioral parity targets in the reference:
- ``AdaptiveKLController`` / ``FixedKLController`` (``trlx/models/modeling_ppo.py:34-66``)
- ``PPOConfig.get_advantages_and_returns`` (``modeling_ppo.py:134-170``) —
  here a reverse ``lax.scan`` instead of a Python loop over T, so it traces
  into one fused XLA op.
- ``PPOConfig.loss`` (``modeling_ppo.py:172-233``) — clipped policy + clipped
  value loss with masked means and the same stats keys (approx-KL k3
  estimator, clipfracs, padding percentage).
"""

from dataclasses import dataclass, field
from typing import Any, ClassVar, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.method_configs import MethodConfig, register_method
from trlx_tpu.observability.dynamics import loss_sketches
from trlx_tpu.utils.stats import get_tensor_stats, whiten
from trlx_tpu.utils import flatten_dict


class AdaptiveKLController:
    """Adaptive KL coefficient from Ziegler et al. (1909.08593 §2.2).

    β is multiplied by ``1 + clip(KL/target - 1, ±0.2) · n/horizon`` after
    each round of rollouts. Host-side scalar state, folded into the compiled
    step as an argument (so updating it never triggers a recompile).

    A non-finite ``current_kl`` (one bad batch) is *skipped* rather than
    folded in — multiplying by NaN would poison ``self.value`` forever, and
    β reaches every subsequent reward via ``kl_penalty_rewards``. Skips are
    counted in :attr:`skipped` and surfaced as the ``health/kl_ctl_skips``
    gauge (trainer/ppo.py ``post_backward_callback``).
    """

    def __init__(self, init_kl_coef: float, target: float, horizon: int):
        self.value = float(init_kl_coef)
        self.target = target
        self.horizon = horizon
        self.skipped = 0

    def update(self, current_kl: float, n_steps: int) -> None:
        if not np.isfinite(current_kl):
            self.skipped += 1
            return
        proportional_error = float(np.clip(current_kl / self.target - 1, -0.2, 0.2))
        self.value *= 1 + proportional_error * n_steps / self.horizon


class FixedKLController:
    """Constant KL coefficient."""

    def __init__(self, kl_coef: float):
        self.value = float(kl_coef)

    def update(self, current_kl: float, n_steps: int) -> None:
        pass


@dataclass
@register_method("PPOConfig")
class PPOConfig(MethodConfig):
    """Hyperparameters of PPO (field-compatible with the reference's
    ``PPOConfig``, ``trlx/models/modeling_ppo.py:74-133``).

    :param ppo_epochs: inner optimization epochs per rollout batch
    :param num_rollouts: experiences to collect before each learning phase
    :param chunk_size: rollout generation batch size
    :param init_kl_coef: initial β of the KL penalty vs the frozen reference
    :param target: adaptive-KL target (None → fixed controller)
    :param horizon: adaptive-KL horizon
    :param gamma: discount
    :param lam: GAE λ
    :param cliprange: PPO ratio clip ε
    :param cliprange_value: value clip range
    :param vf_coef: value-loss coefficient
    :param scale_reward: "running" | "ref" | None/"ignored"
    :param ref_mean/ref_std: fixed scaling moments for ``scale_reward="ref"``
    :param cliprange_reward: clip of environment reward
    :param iw_correction: off-policy importance-weight correction for
        async/disaggregated collection (docs/ASYNC_RL.md). ``"off"``
        (default — the loss is byte-for-byte the serial objective) or
        ``"clip"``: the policy-gradient term is multiplied per token by the
        truncated behavior ratio ``min(exp(old_logprobs −
        behavior_logprobs), iw_clip)``. ``old_logprobs`` are the proximal
        anchor (the scoring forward under the actor's newest params at
        chunk completion); ``behavior_logprobs`` are the sampler's exact
        per-token logprobs, which with in-flight mid-rollout weight sync
        come from a *mixture* of param versions — the ratio corrects the
        proximal/behavior mismatch, truncation bounds its variance
        (V-trace/TIS-style; PipelineRL arxiv 2509.19128).
    :param iw_clip: truncation bound of the behavior ratio.
    :param loss_kernel: learner-step compute program. ``"xla"`` (default)
        runs the staged chain — :meth:`get_advantages_and_returns` then
        :meth:`loss` as separate XLA programs; ``"pallas"`` fuses GAE,
        whitening, the clipped losses, and the stats/sketches into one
        Pallas program per step (``ops/fused_loss.py``), bit-identical in
        loss/grads/stats to the staged path. Validated at trainer
        construction (``trainer/base.py``) like ``engine.decode_kernel``.
    :param gen_kwargs: sampling kwargs for rollouts/eval
    :param gen_experience_kwargs: optional distinct sampling kwargs for rollouts
    """

    #: loss_kernel values this method can host ("pallas" needs the
    #: GAE/value-head loss shape — GRPO narrows this to ("xla",))
    LOSS_KERNELS: ClassVar[Tuple[str, ...]] = ("xla", "pallas")

    name: str = "PPOConfig"
    ppo_epochs: int = 4
    num_rollouts: int = 128
    chunk_size: int = 128
    init_kl_coef: float = 0.05
    target: Optional[float] = 6.0
    horizon: int = 10000
    gamma: float = 1.0
    lam: float = 0.95
    cliprange: float = 0.2
    cliprange_value: float = 0.2
    vf_coef: float = 1.0
    scale_reward: Optional[str] = None
    ref_mean: Optional[float] = None
    ref_std: Optional[float] = None
    cliprange_reward: float = 10.0
    iw_correction: str = "off"
    iw_clip: float = 2.0
    loss_kernel: str = "xla"
    gen_kwargs: Dict[str, Any] = field(default_factory=dict)
    gen_experience_kwargs: Optional[Dict[str, Any]] = None

    def kl_controller(self):
        if self.target is None:
            return FixedKLController(self.init_kl_coef)
        return AdaptiveKLController(self.init_kl_coef, self.target, self.horizon)

    def get_advantages_and_returns(
        self,
        values: jax.Array,  # [B, R]
        rewards: jax.Array,  # [B, R]
        mask: Optional[jax.Array] = None,  # [B, R] response mask
        use_whitening: bool = True,
    ) -> Tuple[jax.Array, jax.Array]:
        """GAE advantages and returns over the response window.

        Reverse-time ``lax.scan``:
            δ_t = r_t + γ V_{t+1} - V_t;  A_t = δ_t + γλ A_{t+1}.
        Positions beyond a sample's true response end must carry zero
        rewards/values (enforced by ``mask`` upstream) so padding contributes
        nothing — the reference instead slices ragged per-sample tensors
        (``accelerate_ppo_trainer.py:450-455``); fixed [B, R] blocks + masks is
        the shape-stable TPU redesign.
        """
        values = values.astype(jnp.float32)
        rewards = rewards.astype(jnp.float32)
        next_values = jnp.concatenate(
            [values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1
        )
        deltas = rewards + self.gamma * next_values - values  # [B, R]

        def backward(lastgaelam, delta_t):
            adv = delta_t + self.gamma * self.lam * lastgaelam
            return adv, adv

        _, adv_rev = jax.lax.scan(
            backward,
            jnp.zeros(values.shape[0], dtype=jnp.float32),
            jnp.flip(deltas, axis=1).T,  # scan over time-major reversed
        )
        advantages = jnp.flip(adv_rev.T, axis=1)
        returns = advantages + values
        if use_whitening:
            advantages = whiten(advantages, mask)
        # returns is stop-gradient'd alongside advantages: it is the value
        # loss's regression TARGET, not a prediction. In the trainer it is
        # built from batch constants (rollout values + rewards) so no
        # parameter gradient reaches it there either way — the stop makes
        # the no-leak property local to this function instead of an
        # accident of the call site, and makes the fused kernel's
        # targets-are-constants treatment (ops/fused_loss.py) exact by
        # definition (grad-equality pinned in tests/test_fused_loss.py).
        return (
            jax.lax.stop_gradient(advantages),
            jax.lax.stop_gradient(returns),
        )

    def loss(
        self,
        logprobs: jax.Array,  # [B, R] new per-token logprobs
        values: jax.Array,  # [B, R] new value predictions
        old_logprobs: jax.Array,  # [B, R] behavior-policy logprobs
        old_values: jax.Array,  # [B, R]
        advantages: jax.Array,  # [B, R]
        returns: jax.Array,  # [B, R]
        mask: jax.Array,  # [B, R] 1 on real response tokens
        behavior_logprobs: Optional[jax.Array] = None,  # [B, R] sampler logprobs
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Clipped-ratio policy loss + clipped value loss; masked sums / n.

        ``behavior_logprobs`` (async collection, ``iw_correction: clip``
        only) multiplies the pg term by the truncated proximal/behavior
        ratio — ``None`` keeps the serial objective byte-for-byte."""
        mask = mask.astype(jnp.float32)
        logprobs = logprobs.astype(jnp.float32)
        values = values.astype(jnp.float32)
        n = jnp.maximum(mask.sum(), 1.0)

        values_clipped = jnp.clip(
            values, old_values - self.cliprange_value, old_values + self.cliprange_value
        )
        vf_loss1 = jnp.square(values - returns)
        vf_loss2 = jnp.square(values_clipped - returns)
        vf_loss = 0.5 * jnp.sum(jnp.maximum(vf_loss1, vf_loss2) * mask) / n
        vf_clipfrac = jnp.sum((vf_loss2 > vf_loss1).astype(jnp.float32) * mask) / n

        log_ratio = (logprobs - old_logprobs) * mask
        ratio = jnp.exp(log_ratio)
        # k3 KL estimator (Schulman): E[(r - 1) - log r]
        approx_kl = jax.lax.stop_gradient(jnp.mean((ratio - 1) - log_ratio))

        pg_loss1 = -advantages * ratio
        pg_loss2 = -advantages * jnp.clip(ratio, 1.0 - self.cliprange, 1.0 + self.cliprange)
        iw_stats = {}
        if behavior_logprobs is not None and self.iw_correction != "off":
            rho, iw_stats = iw_weights(
                old_logprobs, behavior_logprobs, mask, self.iw_clip, n
            )
            pg_loss1 = pg_loss1 * rho
            pg_loss2 = pg_loss2 * rho
        pg_loss = jnp.sum(jnp.maximum(pg_loss1, pg_loss2) * mask) / n
        pg_clipfrac = jnp.sum((pg_loss2 > pg_loss1).astype(jnp.float32) * mask) / n

        loss = pg_loss + self.vf_coef * vf_loss

        dist = {}
        if self.dist_sketches:
            # stop-gradient'd histograms of the loss's own intermediates
            # (observability/dynamics.py) — ride the stats fetch, feed
            # nothing back, so the objective is bit-identical either way
            dist = loss_sketches(
                {
                    "log_ratio": (log_ratio, mask),
                    "kl": ((ratio - 1) - log_ratio, mask),
                    "advantages": (advantages, mask),
                    "value_error": (values - returns, mask),
                }
            )

        stats = dict(
            **iw_stats,
            **dist,
            losses=dict(total_loss=loss, policy_loss=pg_loss, value_loss=vf_loss),
            values=dict(
                get_tensor_stats(values, mask, n),
                values_error=jnp.sum(jnp.square((values - returns) * mask)) / n,
                clipfrac=vf_clipfrac,
            ),
            old_values=get_tensor_stats(old_values, mask, n),
            returns=get_tensor_stats(returns, mask, n),
            policy=dict(approx_kl=approx_kl, clipfrac=pg_clipfrac),
            ratio=jnp.sum(ratio * mask) / n,
            padding_percentage=1.0 - n / mask.size,
        )
        return loss, flatten_dict(stats)

    def loss_fused(
        self,
        logprobs: jax.Array,  # [B, R] new per-token logprobs
        values: jax.Array,  # [B, R] new value predictions
        old_logprobs: jax.Array,  # [B, R] proximal-anchor logprobs
        old_values: jax.Array,  # [B, R] rollout values (GAE input)
        rewards: jax.Array,  # [B, R] per-token KL-penalty rewards
        mask: jax.Array,  # [B, R] response mask
        behavior_logprobs: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """The ``loss_kernel: pallas`` program: GAE + whitening +
        :meth:`loss` as one fused Pallas kernel (``ops/fused_loss.py``)
        instead of staged XLA programs — bit-identical loss/grads/stats.
        Note the different seam: the fused program takes ``rewards`` and
        computes advantages/returns *inside* the kernel, so callers skip
        :meth:`get_advantages_and_returns` entirely."""
        from trlx_tpu.ops.fused_loss import fused_ppo_loss  # late: ops import us

        return fused_ppo_loss(
            self,
            logprobs,
            values,
            old_logprobs,
            old_values,
            rewards,
            mask,
            behavior_logprobs,
        )


def iw_weights(
    old_logprobs: jax.Array,  # [B, R] proximal-anchor logprobs (scoring fwd)
    behavior_logprobs: jax.Array,  # [B, R] sampler's exact behavior logprobs
    mask: jax.Array,  # [B, R] float response mask
    clip: float,
    n: jax.Array,  # masked token count
) -> Tuple[jax.Array, Dict[str, Any]]:
    """Truncated per-token importance weights ``min(exp(old − behavior),
    clip)`` for off-policy (async/stale) samples, with their diagnostics.
    Shared by the PPO and GRPO losses (docs/ASYNC_RL.md "IW correction")."""
    log_rho = (
        old_logprobs.astype(jnp.float32) - behavior_logprobs.astype(jnp.float32)
    ) * mask
    raw = jnp.exp(log_rho)
    rho = jax.lax.stop_gradient(jnp.minimum(raw, clip))
    stats = {
        "iw": dict(
            rho_mean=jnp.sum(rho * mask) / n,
            rho_clipfrac=jnp.sum((raw > clip).astype(jnp.float32) * mask) / n,
        )
    }
    return rho, stats


def kl_penalty_rewards(
    logprobs: jax.Array,  # [B, R] policy logprobs of sampled tokens
    ref_logprobs: jax.Array,  # [B, R] reference logprobs of the same tokens
    response_mask: jax.Array,  # [B, R]
    scores: jax.Array,  # [B] scalar task rewards
    kl_coef: jax.Array,  # scalar β
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Per-token rewards = −β·(logπ − logπ_ref), with the scalar task score
    added at each sample's final response token.

    Returns ``(rewards [B, R], (mean_kl, mean_kl_per_sequence))``:
    ``mean_kl`` is the per-token mean of the k3 estimator over the whole
    [B, R] block — exactly what the reference feeds the adaptive KL
    controller (``accelerate_ppo_trainer.py:431-461``); the per-sequence
    mean (sum over tokens, mean over samples) is reported in stats.
    """
    mask = response_mask.astype(jnp.float32)
    log_ratio = (logprobs - ref_logprobs) * mask
    rewards = -kl_coef * log_ratio
    # index of last real token per row: sum(mask)-1 (clipped for empty rows)
    ends = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
    rewards = rewards.at[jnp.arange(rewards.shape[0]), ends].add(scores)
    ratio = jnp.exp(log_ratio)
    k3 = (ratio - 1) - log_ratio
    mean_kl = jnp.mean(k3)  # per-token mean (controller input)
    mean_kl_per_seq = jnp.mean(jnp.sum(k3 * mask, axis=1))
    return rewards * mask, (mean_kl, mean_kl_per_seq)


def kl_penalty_rewards_np(logprobs, ref_logprobs, response_mask, scores, kl_coef):
    """Host (numpy) twin of :func:`kl_penalty_rewards` — same math on the
    already-fetched [B, R] arrays. The reward assembly depends on the
    host-side ``reward_fn`` scores, so computing it here lets the scoring
    forward be dispatched *before* the host scores exist, collapsing the
    rollout loop to a single device→host sync per batch (the sync dominates
    wall time on tunneled/remote TPU setups)."""
    import numpy as np

    mask = np.asarray(response_mask, np.float32)
    log_ratio = (np.asarray(logprobs) - np.asarray(ref_logprobs)) * mask
    rewards = -float(kl_coef) * log_ratio
    ends = np.maximum(mask.sum(axis=1).astype(np.int32) - 1, 0)
    rewards[np.arange(rewards.shape[0]), ends] += np.asarray(scores, np.float32)
    k3 = (np.exp(log_ratio) - 1) - log_ratio
    mean_kl = float(k3.mean())
    mean_kl_per_seq = float((k3 * mask).sum(axis=1).mean())
    return rewards * mask, (mean_kl, mean_kl_per_seq)
