"""Reward model: LM backbone + scalar head, pairwise preference training.

Reference: ``GPTRewardModel``
(``examples/summarize_rlhf/reward_model/reward_model.py:6-104``) — a causal
LM whose scalar head scores every position; training compares chosen vs
rejected continuations of the same prompt with ``-log σ(r_c − r_r)`` averaged
over the positions from the first diverging token to the longer sequence's
end, and inference reads the score at the last non-pad token.

TPU redesign: the reference loops over the batch in Python (dynamic
``nonzero`` slicing per pair). Here divergence/end indices become masks over
the fixed ``[B, T]`` block (argmax of the mismatch indicator, masked means),
so the whole loss is one fused jitted program — no host control flow, static
shapes, MXU-friendly.
"""

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from trlx_tpu.models.transformer import (
    CausalTransformer,
    TransformerConfig,
    param_with_axes,
)


class RewardModel(nn.Module):
    """Causal LM + per-position scalar reward head (bias-free, f32)."""

    config: TransformerConfig

    def setup(self):
        self.backbone = CausalTransformer(self.config, name="backbone")
        self.r_head = nn.Dense(
            1,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=self.config.param_dtype,
            kernel_init=param_with_axes(nn.initializers.normal(0.02), ("embed", "head_out")),
            name="r_head",
        )

    def __call__(
        self, input_ids: jax.Array, attention_mask: Optional[jax.Array] = None
    ) -> Dict[str, Any]:
        out = self.backbone(input_ids, attention_mask=attention_mask)
        rewards = self.r_head(out["hidden_states"].astype(jnp.float32))[..., 0]
        return {"rewards": rewards, "hidden_states": out["hidden_states"]}


def end_scores(rewards: jax.Array, attention_mask: jax.Array) -> jax.Array:
    """Reward at each sequence's last non-pad position ([B, T] → [B])."""
    lengths = jnp.maximum(jnp.sum(attention_mask, axis=1).astype(jnp.int32), 1)
    return jnp.take_along_axis(rewards, (lengths - 1)[:, None], axis=1)[:, 0]


def pairwise_reward_loss(
    chosen_rewards: jax.Array,  # [B, T]
    rejected_rewards: jax.Array,  # [B, T]
    chosen_ids: jax.Array,  # [B, T] right-padded
    rejected_ids: jax.Array,  # [B, T]
    chosen_mask: jax.Array,  # [B, T]
    rejected_mask: jax.Array,  # [B, T]
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Masked-vectorized preference loss (semantics of the reference's
    per-pair loop): mean over positions in ``[divergence, end)`` of
    ``-log σ(r_chosen − r_rejected)``, where divergence is the first token
    where the pair differs and end covers the longer of the two sequences."""
    T = chosen_ids.shape[1]
    positions = jnp.arange(T)[None, :]

    differs = (chosen_ids != rejected_ids) | (chosen_mask != rejected_mask)
    any_diff = jnp.any(differs, axis=1)
    div_ix = jnp.argmax(differs, axis=1)  # first True (0 if none)
    c_len = jnp.sum(chosen_mask, axis=1).astype(jnp.int32)
    r_len = jnp.sum(rejected_mask, axis=1).astype(jnp.int32)
    end_ix = jnp.maximum(c_len, r_len)

    span = (positions >= div_ix[:, None]) & (positions < end_ix[:, None])
    span = span & any_diff[:, None]  # identical pairs contribute nothing
    n = jnp.maximum(jnp.sum(span, axis=1), 1)

    delta = chosen_rewards - rejected_rewards
    per_pos = -jax.nn.log_sigmoid(delta) * span
    per_pair = jnp.sum(per_pos, axis=1) / n
    n_pairs = jnp.maximum(jnp.sum(any_diff), 1)
    loss = jnp.sum(per_pair * any_diff) / n_pairs

    c_end = end_scores(chosen_rewards, chosen_mask)
    r_end = end_scores(rejected_rewards, rejected_mask)
    acc = jnp.sum((c_end > r_end) * any_diff) / n_pairs
    stats = {
        "reward/loss": loss,
        "reward/accuracy": acc,
        "reward/chosen_end_mean": jnp.mean(c_end),
        "reward/rejected_end_mean": jnp.mean(r_end),
        "reward/margin": jnp.mean((c_end - r_end) * any_diff),
    }
    return loss, stats


def reward_loss_fn(
    module: RewardModel,
    params: Any,
    batch: Dict[str, jax.Array],
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One fused forward over the stacked chosen‖rejected batch + loss
    (the reference concatenates the halves the same way)."""
    ids = jnp.concatenate([batch["chosen_ids"], batch["rejected_ids"]], axis=0)
    mask = jnp.concatenate([batch["chosen_mask"], batch["rejected_mask"]], axis=0)
    rewards = module.apply({"params": params}, ids, attention_mask=mask)["rewards"]
    B = batch["chosen_ids"].shape[0]
    return pairwise_reward_loss(
        rewards[:B], rewards[B:],
        batch["chosen_ids"], batch["rejected_ids"],
        batch["chosen_mask"], batch["rejected_mask"],
    )


def build_reward_model(model_config, parallel=None, seed: int = 0):
    """ModelConfig → (module, params, tcfg), HF backbone import included."""
    from trlx_tpu.models.builder import (
        _import_hf_backbone,
        resolve_transformer_config,
    )

    tcfg, hf_path = resolve_transformer_config(model_config, parallel)
    module = RewardModel(tcfg)
    params = module.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    if hf_path is not None:
        from trlx_tpu.models.hf_interop import load_pretrained

        hf_params, _ = load_pretrained(hf_path)
        backbone = hf_params["backbone"]
        if tcfg.scan_layers:
            from trlx_tpu.models.transformer import stack_layer_params

            backbone = stack_layer_params(backbone, tcfg.num_layers)
        params = _import_hf_backbone(params, "reward", backbone, tcfg.param_dtype)
    return module, params, tcfg
