# Developer entry points. `make lint` is the same gate CI runs
# (.github/workflows/lint.yml) and the tier-1 self-run asserts
# (tests/test_analysis.py): graftlint over trlx_tpu/ AND scripts/ against
# the committed baseline, with a SARIF artifact for inline annotation.
# It needs NO ML dependencies — `trlx_tpu.analysis` is stdlib-only
# (pure-AST; the package root's `train` is a lazy attribute).

.PHONY: lint lint-sarif test

lint:
	python scripts/lint.py

lint-sarif:
	python scripts/lint.py --sarif graftlint.sarif

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider
