"""Benchmark: PPO experience+train throughput, ppo_sentiments-shaped.

Measures end-to-end PPO samples/sec on the BASELINE.json north-star task
shape: GPT-2-small (124M, real dims, random init — no network), prompts of 64
tokens, 40 new tokens per rollout (the reference ppo_sentiments gen_kwargs,
``trlx/data/default_configs.py:54``), chunk 128, 4 PPO epochs per batch of
128. One timed unit = collect 128 rollouts (jitted KV-cache decode + scoring
fwd + hydra-ref fwd + KL) and run the 4×1 optimization steps — the same
work AcceleratePPOTrainer does per epoch (SURVEY.md §3.2-3.3).

Baseline: single-A100 trlx ppo_sentiments ≈ 40 samples/s (estimate from the
reference's W&B `trlx-references` runs: ~1k rollouts+updates in ~25 min);
``vs_baseline`` = samples_per_sec / 40.0 (target ≥3.0 per BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

A100_BASELINE_SAMPLES_PER_SEC = 40.0


def main():
    import jax

    from trlx_tpu.data.default_configs import default_ppo_config
    from trlx_tpu.pipeline import get_pipeline
    from trlx_tpu.trainer import get_trainer
    import trlx_tpu.trainer.ppo  # noqa: F401
    import trlx_tpu.pipeline.offline_pipeline  # noqa: F401

    n_dev = jax.device_count()
    chunk = int(os.environ.get("BENCH_CHUNK", 128))
    # byte-level prompts, 64 tokens each; bucketing keeps one compiled shape
    prompt_tokens = 64
    max_new = 40

    config = default_ppo_config().evolve(
        train=dict(
            seq_length=prompt_tokens + max_new,
            batch_size=chunk,
            total_steps=1_000_000,
            eval_interval=1_000_000,
            checkpoint_interval=1_000_000,
            epochs=1,
            checkpoint_dir="/tmp/trlx_tpu_bench",
            tracker=None,
        ),
        model=dict(model_path="builtin:gpt2-small", num_layers_unfrozen=2),
        parallel=dict(data=-1, fsdp=1, model=1),
        method=dict(
            num_rollouts=chunk,
            chunk_size=chunk,
            ppo_epochs=4,
            gen_kwargs=dict(
                max_new_tokens=max_new, top_k=0, top_p=1.0, do_sample=True
            ),
        ),
    )

    def reward_fn(samples, prompts, outputs, **kwargs):
        return [float(sum(c in "aeiou" for c in o)) for o in outputs]

    trainer = get_trainer(config.train.trainer)(
        config=config, reward_fn=reward_fn, metric_fn=None, stop_sequences=[]
    )

    rng = np.random.RandomState(0)
    prompts = ["".join(chr(97 + c) for c in rng.randint(0, 26, prompt_tokens)) for _ in range(512)]
    pipeline = get_pipeline(config.train.pipeline)(prompts, prompt_tokens, trainer.tokenizer)
    trainer.add_prompt_pipeline(pipeline)

    def one_cycle():
        trainer.store.clear_history()
        trainer.make_experience(chunk)
        loader = trainer.store.create_loader(
            config.train.batch_size,
            shuffle=True,
            query_length=prompt_tokens,
            response_length=max_new,
        )
        for batch in loader:
            for _ in range(config.method.ppo_epochs):
                stats = trainer.train_step(batch)
        jax.block_until_ready(trainer.state.params)
        return stats

    one_cycle()  # warmup: compiles decode, score, train programs
    n_cycles = int(os.environ.get("BENCH_CYCLES", 3))
    t0 = time.time()
    for _ in range(n_cycles):
        stats = one_cycle()
    dt = time.time() - t0

    samples_per_sec = n_cycles * chunk / dt
    per_chip = samples_per_sec / max(n_dev, 1)
    print(
        json.dumps(
            {
                "metric": "ppo_sentiments-shaped e2e throughput (gpt2-small, 64+40 tok)",
                "value": round(samples_per_sec, 3),
                "unit": "samples/sec",
                "vs_baseline": round(samples_per_sec / A100_BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
