"""Benchmark: PPO experience+train throughput, ppo_sentiments-shaped.

Measures end-to-end PPO samples/sec on the BASELINE.json north-star task
shape: GPT-2-small (124M, real dims, random init — no network), prompts of 64
tokens, 40 new tokens per rollout (the reference ppo_sentiments gen_kwargs,
``trlx/data/default_configs.py:54``), chunk 128, 4 PPO epochs per batch of
128. One timed unit = collect 128 rollouts (jitted KV-cache decode + scoring
fwd + hydra-ref fwd + KL) and run the 4×1 optimization steps — the same
work AcceleratePPOTrainer does per epoch (SURVEY.md §3.2-3.3).

Baseline denominator (``A100_BASELINE_SAMPLES_PER_SEC = 40``): the reference
publishes no throughput numbers (SURVEY.md §6), so this is a derived
estimate, stated openly.  Derivation: the reference ppo_sentiments config
(``trlx/data/default_configs.py:15-57``) runs 10k optimization steps of
batch 128 with ``num_rollouts=128``/``ppo_epochs=4`` — i.e. one 128-rollout
collection (128×40-token KV-cached decodes + scoring fwd + hydra-ref fwd)
per 4 updates.  An A100 runs gpt2-small (124M) batched decode at roughly
25-35ms/step at batch 128 in fp16 HF ``generate`` (memory-bound decode:
~0.25GB weights × 2 reads per token-step against ~1.5TB/s effective HBM,
plus attention/softmax and per-step host sync overhead), giving ~1.0-1.4s
per 40-token rollout chunk, ~0.4s for the two scoring forwards, and ~0.4s
for 4 updates — ≈2s per 128-sample cycle ⇒ ~55-65 samples/s upper bound,
degraded in practice by HF generate's per-step Python/host overhead and the
reference's host-side re-tokenization between decode and scoring
(``accelerate_ppo_trainer.py:329-348``) to ~40 samples/s.  ``vs_baseline`` =
samples_per_sec / 40.0 (target ≥3.0 per BASELINE.json).

Robustness: the TPU backend can be transiently unavailable (single-tenant
chip wedged by a stale session from a killed process — this killed the r1
AND r2 bench windows).  Init is probed in throwaway subprocesses (SIGTERM
only, never SIGKILL — killing a mid-claim process is what causes the wedge)
and retried with backoff for ``BENCH_ACCEL_WAIT`` seconds (default 15 min —
short enough that the CPU fallback still finishes inside the driver's bench
window; r3's 40-min default overran it, rc=124 with no artifact); if the
accelerator never comes up, the bench falls back to forced-CPU with a
reduced work size so it still emits a parsable JSON line (tagged
``[cpu-fallback]``, with the wedge status stamped into the ``note``).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

A100_BASELINE_SAMPLES_PER_SEC = 40.0


_ORPHANED_PROBES = 0


def _probe_accelerator(timeout_s: float) -> bool:
    """Try TPU backend init in a THROWAWAY subprocess with a hard timeout.

    A contended single-tenant chip can make ``jax.devices()`` *hang* on the
    tunnel claim (not just raise UNAVAILABLE) — a stale session from a killed
    process holds the chip until the server notices (observed to take tens of
    minutes; it ate both the r1 and r2 bench windows). Probing in a
    subprocess converts that hang into a retryable failure instead of
    wedging the bench past the driver's timeout.

    A hung probe is NEVER SIGKILLed: SIGKILL on a process mid-claim is
    exactly what wedges the chip for the next session. Escalation is
    SIGTERM → grace → SIGTERM → grace → orphan (leave it running and move
    on). A probe blocked *waiting* for the claim holds nothing and dies
    cleanly on SIGTERM; one that ignores SIGTERM is likely inside the claim
    handshake, where killing it is the one action guaranteed to make things
    worse. Orphans are capped — see ``_init_devices``.
    """
    import subprocess

    global _ORPHANED_PROBES
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        for _ in range(2):
            proc.terminate()  # SIGTERM only — never SIGKILL (chip wedge)
            try:
                proc.wait(timeout=30)
                return False
            except subprocess.TimeoutExpired:
                continue
        _ORPHANED_PROBES += 1
        print(
            f"bench: probe pid {proc.pid} ignored SIGTERM — orphaning it "
            f"(orphans={_ORPHANED_PROBES}); NOT escalating to SIGKILL",
            file=sys.stderr,
        )
        return False


def _init_devices():
    """``jax.devices()`` with a long accelerator-wait horizon, then
    forced-CPU fallback.

    Keep re-probing with backoff for ``BENCH_ACCEL_WAIT`` seconds (default
    900 — the budget must leave the CPU-fallback bench room to finish
    inside the driver's window) before giving up, logging every attempt's
    outcome to stderr.

    Returns ``(devices, fallback_exc, attempts)`` — ``fallback_exc`` is None
    unless we gave up on the accelerator and dropped to CPU.
    """
    import jax

    # r3 post-mortem: a 2400s probe budget exceeded the driver's own bench
    # timeout (BENCH_r03.json rc=124 with no JSON line at all). The probe
    # horizon must leave room for the CPU-fallback bench to complete inside
    # the driver window, so a wedged round still produces an artifact.
    wait_budget = float(os.environ.get("BENCH_ACCEL_WAIT", 900.0))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120.0))
    deadline = time.time() + wait_budget
    last_err = None
    attempt = 0
    # a probe shorter than this can't tell "down" from "slow init" — below
    # it, skip and fall back rather than burn the fallback bench's window
    probe_floor = min(30.0, probe_timeout)
    while True:
        # never LAUNCH an attempt whose own timeout overruns the remaining
        # wait budget: BENCH_r05 shows attempt 6 finishing at "-45s of wait
        # budget left" — those overrun seconds come straight out of the
        # CPU-fallback bench's share of the driver window. Clamp the probe
        # to the remaining budget; once that's below the useful floor, skip
        # and fall back immediately.
        remaining_before = deadline - time.time()
        this_timeout = min(probe_timeout, remaining_before)
        if this_timeout < probe_floor:
            print(
                f"bench: skipping probe attempt {attempt + 1}: "
                f"{remaining_before:.0f}s of wait budget left < useful probe "
                f"floor {probe_floor:.0f}s — falling back now",
                file=sys.stderr,
            )
            last_err = last_err or RuntimeError(
                f"accelerator wait budget ({wait_budget:.0f}s) exhausted "
                "below the probe floor; no probe attempted"
            )
            break
        attempt += 1
        t0 = time.time()
        try:
            if not _probe_accelerator(this_timeout):
                raise RuntimeError(
                    f"accelerator init probe failed/hung (> {this_timeout:.0f}s)"
                )
            print(
                f"bench: accelerator up on attempt {attempt} "
                f"(waited {time.time() + wait_budget - deadline:.0f}s total)",
                file=sys.stderr,
            )
            return jax.devices(), None, attempt
        except Exception as e:  # backend init failure (e.g. contended chip)
            last_err = e
            remaining = deadline - time.time()
            print(
                f"bench: backend init failed (attempt {attempt}, "
                f"{time.time() - t0:.0f}s, {remaining:.0f}s of wait budget "
                f"left): {e}",
                file=sys.stderr,
            )
            try:
                import jax.extend.backend

                jax.extend.backend.clear_backends()
            except Exception:
                pass
            if remaining <= 0 or _ORPHANED_PROBES > 2:
                if _ORPHANED_PROBES > 2:
                    print(
                        "bench: too many orphaned probes — stopping probes to "
                        "avoid a claim pileup",
                        file=sys.stderr,
                    )
                break
            # backoff 30→60s; a wedge clears server-side, polling faster
            # than ~1/min buys nothing
            time.sleep(min(30.0 + 5.0 * attempt, 60.0, max(remaining, 1.0)))
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    except Exception:
        pass
    return jax.devices(), last_err, attempt


_PROMPT_TOKENS = 64
_MAX_NEW = 40


def _bench_ppo_config(model_path, chunk, ckpt_dir, model_kwargs=None, parallel_kwargs=None):
    """The ppo_sentiments-shaped bench config — one definition for the
    gpt2-small headline and the gpt2-xl stage, so both measure the same
    work per sample."""
    from trlx_tpu.data.default_configs import default_ppo_config

    return default_ppo_config().evolve(
        train=dict(
            seq_length=_PROMPT_TOKENS + _MAX_NEW,
            batch_size=chunk,
            total_steps=1_000_000,
            eval_interval=1_000_000,
            checkpoint_interval=1_000_000,
            epochs=1,
            checkpoint_dir=ckpt_dir,
            tracker=None,
        ),
        model=dict(
            model_path=model_path,
            num_layers_unfrozen=2,
            **(model_kwargs or {}),
        ),
        parallel=dict(data=-1, fsdp=1, model=1, **(parallel_kwargs or {})),
        method=dict(
            num_rollouts=chunk,
            chunk_size=chunk,
            ppo_epochs=4,
            gen_kwargs=dict(
                max_new_tokens=_MAX_NEW, top_k=0, top_p=1.0, do_sample=True
            ),
        ),
    )


def _build_bench_trainer(config, reward_fn, n_prompts):
    from trlx_tpu.pipeline import get_pipeline
    from trlx_tpu.trainer import get_trainer

    trainer = get_trainer(config.train.trainer)(
        config=config, reward_fn=reward_fn, metric_fn=None, stop_sequences=[]
    )
    rng = np.random.RandomState(0)
    prompts = [
        "".join(chr(97 + c) for c in rng.randint(0, 26, _PROMPT_TOKENS))
        for _ in range(n_prompts)
    ]
    trainer.add_prompt_pipeline(
        get_pipeline(config.train.pipeline)(prompts, _PROMPT_TOKENS, trainer.tokenizer)
    )
    return trainer


def _make_cycle(trainer, config, chunk):
    """One timed unit: collect ``chunk`` rollouts + ppo_epochs update
    passes — the reference's per-epoch work (SURVEY.md §3.2-3.3)."""
    import jax

    def cycle():
        trainer.store.clear_history()
        trainer.make_experience(chunk)
        loader = trainer.store.create_loader(
            config.train.batch_size,
            shuffle=True,
            query_length=_PROMPT_TOKENS,
            response_length=_MAX_NEW,
        )
        stats = None
        for batch in loader:
            for _ in range(config.method.ppo_epochs):
                t_step = time.perf_counter()
                stats = trainer.train_step(batch)
                # the learn loop owns this counter normally; step-triggered
                # fault-plan entries (BENCH_FAULTS) key off it, so a cycle
                # must advance it too or step:N faults re-fire forever
                trainer.iter_count += 1
                # cluster-telemetry beat (docs/OBSERVABILITY.md "Distributed
                # telemetry"): the learn loop drives this at its step
                # boundaries; the bench cycle mirrors it so the headline
                # carries cluster/step_skew_s (0.0 single-process —
                # max-min over one rank — nonzero on a real pod)
                trainer.obs.cluster.note_step(time.perf_counter() - t_step)
                trainer.obs.cluster.beat(False, step=trainer.iter_count)
        jax.block_until_ready(trainer.state.params)
        return stats

    return cycle


def _program_cycle_flops(config, trainer, chunk):
    """Total FLOPs of one cycle from XLA's cost_analysis of the exact
    compiled generate/score/train_step programs (attention, collectives,
    everything — shared by the headline and xl MFU so they are comparable).
    None when unavailable or nonsensical (the cost model's missing-key
    sentinel is negative).

    The per-device × n_dev accounting is only valid when the batch fully
    shards over the data axes — a replicated batch makes every device
    recompute the same work and the multiply would inflate MFU by up to
    n_dev×. Refuse (None) rather than report a flattering wrong number.
    """
    import jax

    dp = trainer.mesh.shape.get("data", 1) * trainer.mesh.shape.get("fsdp", 1)
    if chunk % dp:
        print(
            f"bench: program-flops MFU skipped (chunk {chunk} does not shard "
            f"over data axes {dp}; per-device accounting would overcount)",
            file=sys.stderr,
        )
        return None
    try:
        from trlx_tpu.perf import hot_program_costs

        costs = hot_program_costs(
            config,
            batch_size=chunk,
            prompt_len=_PROMPT_TOKENS,
            gen_len=_MAX_NEW,
            trainer=trainer,
        )
        flops = (
            costs["generate"]["flops"]
            + costs["score"]["flops"]
            + config.method.ppo_epochs * costs["train_step"]["flops"]
        ) * max(len(jax.devices()), 1)  # cost_analysis is per device
        return flops if flops > 0 else None
    except Exception as e:  # never let accounting kill the artifact
        print(f"bench: program-flops unavailable: {e}", file=sys.stderr)
        return None


def _maybe_xl_stage(on_cpu, peak, reward_fn):
    """On-chip second point at real scale: gpt2-xl (1.5B) e2e PPO cycle on
    the same task shape (round-4 verdict next#1 — a bench window must
    capture more than gpt2-small). Runs strictly AFTER the headline stdout
    line is emitted, so an overrun can only cost this stage. Skipped on CPU
    fallback, on low remaining budget (``BENCH_XL_DEADLINE_S`` after
    process start), or via ``BENCH_XL=0``. Emits its own stderr JSON."""
    import jax

    if on_cpu or os.environ.get("BENCH_XL", "1") == "0":
        return
    deadline = float(os.environ.get("BENCH_XL_DEADLINE_S", "600"))
    if time.time() - _T0 > deadline:
        print(
            f"bench: skipping gpt2-xl stage (past {deadline:.0f}s budget)",
            file=sys.stderr,
        )
        return
    try:
        chunk = int(os.environ.get("BENCH_XL_CHUNK", 16))
        config = _bench_ppo_config(
            "builtin:gpt2-xl",
            chunk,
            "/tmp/trlx_tpu_bench_xl",
            # scan_layers + remat: the 20B-path compile/memory regime,
            # exercised on real silicon at 1.5B
            model_kwargs=dict(model_extra_kwargs=dict(scan_layers=True)),
            parallel_kwargs=dict(remat="full"),
        )
        trainer = _build_bench_trainer(config, reward_fn, n_prompts=128)
        cycle = _make_cycle(trainer, config, chunk)
        cycle()  # warmup/compile
        t0 = time.time()
        cycle()
        dt = time.time() - t0

        xl_flops = _program_cycle_flops(config, trainer, chunk)
        n_dev = max(len(jax.devices()), 1)
        xl_mfu = (
            xl_flops / dt / (peak * n_dev)
            if xl_flops is not None and np.isfinite(peak)
            else None
        )
        print(
            json.dumps(
                {
                    "xl_stage": {
                        "model": "gpt2-xl (1.5B, scan_layers+remat)",
                        "samples_per_sec": round(chunk / dt, 3),
                        "tokens_per_sec": round(
                            chunk * (_PROMPT_TOKENS + _MAX_NEW) / dt, 1
                        ),
                        "mfu": round(xl_mfu, 4) if xl_mfu is not None else None,
                        "cycle_s": round(dt, 2),
                        "chunk": chunk,
                    }
                }
            ),
            file=sys.stderr,
        )
    except Exception as e:  # the stage is additive evidence, never a blocker
        print(f"bench: gpt2-xl stage failed: {e}", file=sys.stderr)


def _elastic_probe(trainer):
    """Untimed shrink-restore probe (docs/RESILIENCE.md "Elastic restore"):
    save the live train state on the full mesh, restore it onto a HALVED
    mesh through the topology-manifest reshard path, and verify every leaf
    round-tripped byte-identically. On a single-device run (CPU fallback)
    the reshard path is forced via the ``topology_shrink`` fault instead —
    same machinery, same byte check. Returns "ok" / "degraded..." for the
    headline's ``elastic_recovery`` field; never raises (the probe is
    evidence, not a gate)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from trlx_tpu.parallel.mesh import MESH_AXES
    from trlx_tpu.resilience import restore_state_elastic
    from trlx_tpu.resilience.faults import FaultPlan, get_active_plan, set_active_plan
    from trlx_tpu.utils.checkpoint import save_state

    t0 = time.time()
    tmp = tempfile.mkdtemp(prefix="trlx_tpu_bench_elastic_")
    mode = "unknown"
    try:
        ckpt = os.path.join(tmp, "checkpoint_0")
        save_state(ckpt, trainer.state, async_save=False)
        devs = jax.devices()
        n = len(devs)
        if n >= 2:
            # a replicated template on half the devices: a genuine topology
            # change (device_count halves), so the manifest mismatch drives
            # the host-side reshard
            half = Mesh(
                np.asarray(devs[: n // 2]).reshape(
                    (n // 2,) + (1,) * (len(MESH_AXES) - 1)
                ),
                MESH_AXES,
            )
            repl = NamedSharding(half, PartitionSpec())
            template = jax.tree_util.tree_map(
                lambda x: (
                    jax.device_put(jnp.zeros(x.shape, x.dtype), repl)
                    if isinstance(x, jax.Array)
                    else x
                ),
                trainer.state,
            )
            restored = restore_state_elastic(ckpt, template)
            mode = f"halved mesh ({n}->{n // 2} devices)"
        else:
            prev = get_active_plan()
            set_active_plan(FaultPlan.parse("topology_shrink@resume:1"))
            try:
                restored = restore_state_elastic(ckpt, trainer.state)
            finally:
                set_active_plan(prev)
            mode = "forced reshard (single device)"
        ok = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree_util.tree_leaves(jax.device_get(restored)),
                jax.tree_util.tree_leaves(jax.device_get(trainer.state)),
            )
        )
        result = "ok" if ok else "degraded"
    except Exception as e:  # evidence, never a blocker
        result = f"degraded: {e}"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(
        json.dumps(
            {
                "elastic_proof": {
                    "mode": mode,
                    "recovery": result,
                    "probe_s": round(time.time() - t0, 2),
                }
            }
        ),
        file=sys.stderr,
    )
    return result


def _flightrec_probe(trainer):
    """Untimed flight-recorder probe (docs/OBSERVABILITY.md "Flight
    recorder"): dump the forensic ring the warmup cycle populated, reload
    the JSON, and verify it actually carries span and metric records —
    proving the black box this build would leave behind on a crash is
    readable and non-empty. Returns "ok" / "degraded..." for the headline's
    ``flight_recorder`` field; never raises (evidence, not a gate)."""
    import shutil
    import tempfile

    t0 = time.time()
    tmp = tempfile.mkdtemp(prefix="trlx_tpu_bench_flightrec_")
    kinds = []
    try:
        path = trainer.obs.dump_flight_record(reason="bench probe", directory=tmp)
        ok = False
        if path:
            with open(path) as f:
                doc = json.load(f)
            records = doc.get("records", [])
            kinds = sorted({r.get("kind") for r in records})
            ok = bool(records) and "span" in kinds and "metric" in kinds
        result = "ok" if ok else "degraded"
    except Exception as e:  # evidence, never a blocker
        result = f"degraded: {e}"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(
        json.dumps(
            {
                "flightrec_proof": {
                    "recovery": result,
                    "record_kinds": kinds,
                    "probe_s": round(time.time() - t0, 2),
                }
            }
        ),
        file=sys.stderr,
    )
    return result


def _serve_probe(trainer):
    """Untimed serving probe (docs/SERVING.md): start the HTTP frontend on
    the serving engine, stream one interactive request over a real socket
    (SSE deltas + done frame, stamped with the published params version),
    then push a synthetic admission flood through the real gate — proving
    this build can answer traffic while training AND shed load with 429s.
    Drains the frontend before returning so the pump thread never competes
    with the timed cycles. Returns "ok" / "degraded..." for the headline's
    ``serving`` field; never raises (evidence, not a gate)."""
    import http.client

    t0 = time.time()
    proof = {}
    try:
        trainer._maybe_start_serving()
        srv = trainer._serve
        if srv is None:
            raise RuntimeError("serve frontend did not start")
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=120)
        conn.request(
            "POST",
            "/v1/generate",
            json.dumps(
                {
                    "prompt_ids": list(range(5, 21)),
                    "seed": 7,
                    "stream": True,
                    "class": "interactive",
                }
            ),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        status = resp.status
        raw = resp.read().decode()
        conn.close()
        streamed, done = 0, None
        for frame in raw.split("\n\n"):
            if not frame.startswith("data: "):
                continue
            payload = json.loads(frame[len("data: "):])
            if "tokens" in payload:
                streamed += len(payload["tokens"])
            elif payload.get("done"):
                done = payload
        flood_rejected = srv.flood_drill()
        flat = srv.flat_metrics()
        ok = (
            status == 200
            and done is not None
            and done.get("n_tokens", 0) > 0
            and streamed == done["n_tokens"]
            and done.get("params_version") is not None
            and flat.get("serve/completed", 0) >= 1
            and flood_rejected > 0
        )
        proof = {
            "http_status": status,
            "streamed_tokens": streamed,
            "params_version": done.get("params_version") if done else None,
            "flood_rejected": flood_rejected,
            "ttft_s": (
                round(float(flat["serve/ttft_p95"]), 4)
                if flat.get("serve/ttft_p95") is not None
                else None
            ),
        }
        result = "ok" if ok else "degraded"
    except Exception as e:  # evidence, never a blocker
        result = f"degraded: {e}"
    finally:
        # tear the frontend down NOW: the timed cycles must not share the
        # host with the serve pump (trainer shutdown re-drains a no-op)
        serve, trainer._serve = trainer._serve, None
        if serve is not None:
            try:
                serve.drain()
            except Exception:
                pass
    proof["recovery"] = result
    proof["probe_s"] = round(time.time() - t0, 2)
    print(json.dumps({"serve_proof": proof}), file=sys.stderr)
    return result


_T0 = time.time()


def main():
    import jax

    global _T0
    _T0 = time.time()
    devices, fallback_err, probe_attempts = _init_devices()
    on_cpu = devices[0].platform == "cpu"
    if fallback_err is not None:
        print(f"bench: accelerator unavailable, CPU fallback: {fallback_err}", file=sys.stderr)
    # self-documenting provenance: device kind + timestamp ride the stderr
    # artifact so a bench capture alone is attributable evidence
    print(
        json.dumps(
            {
                "bench_env": {
                    "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    "platform": devices[0].platform,
                    "device_kind": getattr(devices[0], "device_kind", "?"),
                    "n_devices": len(devices),
                }
            }
        ),
        file=sys.stderr,
    )

    import trlx_tpu.trainer.ppo  # noqa: F401
    import trlx_tpu.pipeline.offline_pipeline  # noqa: F401

    n_dev = len(devices)
    # CPU fallback: shrink the timed unit so the bench finishes under the
    # driver timeout; the resulting number is tagged, not comparable.
    chunk = int(os.environ.get("BENCH_CHUNK", 16 if on_cpu else 128))
    # byte-level prompts, 64 tokens each; bucketing keeps one compiled shape
    prompt_tokens = _PROMPT_TOKENS
    max_new = _MAX_NEW

    config = _bench_ppo_config("builtin:gpt2-small", chunk, "/tmp/trlx_tpu_bench")
    # BENCH_CB=1: run rollouts through the continuous-batching engine (the
    # headline default stays the serial sampler so values remain comparable
    # across rounds; the dedicated A/B lives in
    # `python -m trlx_tpu.benchmark continuous-batching`)
    bench_cb = os.environ.get("BENCH_CB", "0") == "1"
    if bench_cb:
        config = config.evolve(train=dict(continuous_batching=True))
    # BENCH_ENGINE=1: continuous batching over the paged-KV engine with the
    # prefix cache (docs/PERFORMANCE.md engine section) — the headline then
    # carries prefix_hit_rate and kv_blocks_in_use; the dedicated A/B lives
    # in `python -m trlx_tpu.benchmark engine-paged`. BENCH_DECODE_KERNEL
    # selects the paged decode compute (xla | pallas — the in-place
    # paged-attention kernel, docs/PERFORMANCE.md "Pallas kernels").
    bench_engine = os.environ.get("BENCH_ENGINE", "0") == "1"
    if bench_engine:
        config = config.evolve(
            train=dict(continuous_batching=True),
            engine=dict(
                backend="paged", prefix_cache=True,
                decode_kernel=os.environ.get("BENCH_DECODE_KERNEL", "xla"),
            ),
        )

    # BENCH_SPEC=1: speculative continuous batching over the paged engine
    # (engine.speculative, docs/PERFORMANCE.md "Speculative continuous
    # batching") — a tiny draft proposes gamma tokens per round, the policy
    # verifies them in ONE paged forward, per-row RNG keeps every stream
    # bit-identical to a solo speculative run. The headline then carries
    # spec_acceptance_rate; the dedicated A/B lives in
    # `python -m trlx_tpu.benchmark engine-spec`.
    bench_spec = os.environ.get("BENCH_SPEC", "0") == "1"
    if bench_spec:
        config = config.evolve(
            train=dict(continuous_batching=True),
            model=dict(draft_model_path="builtin:gpt2-test", draft_gamma=4),
            engine=dict(backend="paged", prefix_cache=True, speculative=4),
            method=dict(
                gen_kwargs=dict(
                    max_new_tokens=_MAX_NEW, top_k=0, top_p=1.0,
                    do_sample=True, per_row_rng=True,
                )
            ),
        )

    # BENCH_LOSS_KERNEL: learner-step loss compute (xla | pallas). pallas
    # runs GAE + advantage whitening + the clipped PPO losses as ONE fused
    # Pallas program per train step (method.loss_kernel,
    # docs/PERFORMANCE.md "Fused learner kernels") — bit-identical
    # loss/grads/stats to the staged default. The dedicated A/B lives in
    # `python -m trlx_tpu.benchmark loss-kernel`.
    bench_loss_kernel = os.environ.get("BENCH_LOSS_KERNEL", "xla")
    if bench_loss_kernel != "xla":
        config = config.evolve(method=dict(loss_kernel=bench_loss_kernel))

    # BENCH_ASYNC=1: route experience collection through the disaggregated
    # actor/learner split (docs/ASYNC_RL.md) — one actor thread generates
    # the NEXT cycle's rollouts while the timed cycle's ppo_epochs updates
    # run, gated at max_staleness = updates-per-cycle (full overlap, bounded
    # off-policyness). The headline then carries actor_idle_frac and
    # mean_staleness; the committed A/B lives in benchmarks/ASYNC_RL_cpu.json
    # (scripts/bench_async_ab.py).
    bench_async = os.environ.get("BENCH_ASYNC", "0") == "1"
    if bench_async:
        updates_per_cycle = 4  # ppo_epochs × (num_rollouts // batch_size)
        config = config.evolve(
            async_rl=dict(
                enabled=True, mode="thread", num_actors=1,
                max_staleness=updates_per_cycle,
                # default to the collective fleet transport so the headline
                # measures the dissemination tree (BENCH_ASYNC_TRANSPORT=file
                # falls back to the in-memory/file channel); the committed
                # file-vs-collective A/B is benchmarks/ASYNC_TRANSPORT_cpu.json
                transport=os.environ.get("BENCH_ASYNC_TRANSPORT", "collective"),
            ),
            method=dict(iw_correction="clip"),
        )

    # BENCH_SERVE=1: stand up the serving frontend (docs/SERVING.md) on the
    # paged continuous-batching engine — the untimed _serve_probe then
    # streams a real HTTP request end-to-end and runs an admission flood
    # drill before the timed cycles (the frontend is drained first, so the
    # pump never competes with the timed rollouts). The committed A/B lives
    # in benchmarks/SERVE_cpu.json (scripts/bench_serve_ab.py).
    bench_serve = os.environ.get("BENCH_SERVE", "0") == "1"
    if bench_serve:
        config = config.evolve(
            train=dict(continuous_batching=True),
            engine=dict(backend="paged", prefix_cache=True),
            serve=dict(
                enabled=True, host="127.0.0.1", port=0, slots=2,
                max_new_tokens=8, host_tier_blocks=64,
                retain_param_versions=2,
            ),
        )

    # BENCH_FAULTS=1 (default): prove end-to-end recovery on this exact
    # build during the UNTIMED warmup cycle (docs/RESILIENCE.md) — the
    # fault plan fails the first two reward_fn attempts (absorbed by
    # retry/backoff) and poisons the first train step's loss to NaN
    # (absorbed by the on-device update guard). Neither fault can reach the
    # timed cycles: the plan's triggers are spent at call 1-2 / step 0.
    bench_faults = os.environ.get("BENCH_FAULTS", "1") == "1"
    if bench_faults:
        config = config.evolve(
            resilience=dict(
                update_guard="skip",  # the NaN step must not touch weights
                fault_plan="reward_raise@call:1*2; nan_loss@step:0",
                reward_backoff_s=0.05,
            )
        )

    def reward_fn(samples, prompts, outputs, **kwargs):
        return [float(sum(c in "aeiou" for c in o)) for o in outputs]

    trainer = _build_bench_trainer(config, reward_fn, n_prompts=512)
    one_cycle = _make_cycle(trainer, config, chunk)

    one_cycle()  # warmup: compiles decode, score, train programs
    fault_recovery = None
    if bench_faults:
        # the warmup just survived an injected reward outage and a NaN
        # loss; verify both recoveries actually happened before timing
        import jax

        snap = trainer.obs.metrics.snapshot(reset_histograms=False)
        retried = snap.get("resilience/reward_retries", 0) >= 2
        finite = all(
            bool(np.isfinite(np.asarray(leaf)).all())
            for leaf in jax.tree_util.tree_leaves(
                jax.device_get(trainer.state.params)
            )
        )
        fault_recovery = "ok" if (retried and finite) else "degraded"
        print(
            json.dumps(
                {
                    "fault_proof": {
                        "reward_retries": snap.get("resilience/reward_retries", 0),
                        "params_finite_after_nan_step": finite,
                        "recovery": fault_recovery,
                    }
                }
            ),
            file=sys.stderr,
        )
    elastic_recovery = _elastic_probe(trainer) if bench_faults else None
    flight_recorder = _flightrec_probe(trainer) if bench_faults else None
    serving = _serve_probe(trainer) if bench_serve else None
    n_cycles = int(os.environ.get("BENCH_CYCLES", 1 if on_cpu else 3))
    t0 = time.time()
    for _ in range(n_cycles):
        stats = one_cycle()
    dt = time.time() - t0

    samples_per_sec = n_cycles * chunk / dt
    per_chip = samples_per_sec / max(n_dev, 1)
    tag = " [cpu-fallback]" if on_cpu else ""
    if bench_cb:
        tag += " [continuous-batching]"
    if bench_async:
        tag += " [async-rl]"
    if bench_serve:
        tag += " [serve]"
    if bench_loss_kernel != "xla":
        tag += f" [loss-kernel-{bench_loss_kernel}]"
    # self-explanatory wedge context (round-3 verdict next#1): when the
    # single-tenant chip claim is wedged, the artifact itself must say why
    # there is no on-chip number and where the evidence trail lives
    note = None
    if on_cpu and fallback_err is not None:
        note = (
            f"CPU fallback, value NOT comparable to baseline: accelerator "
            f"init failed after {probe_attempts} SIGTERM-only probe attempts "
            f"({fallback_err}); acquisition trail in "
            f"benchmarks/tpu/ACQUISITION_LOG.md"
        )
        # incident context comes from the session that knows it — either
        # BENCH_WEDGE_SINCE in the env, or the maintained status file
        # benchmarks/tpu/WEDGE_STATUS.json (updated/cleared by the builder)
        # — never a source-code default that would mislabel future fallbacks
        wedge_since = os.environ.get("BENCH_WEDGE_SINCE")
        if not wedge_since:
            try:
                status_path = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "benchmarks", "tpu", "WEDGE_STATUS.json",
                )
                with open(status_path) as f:
                    status = json.load(f)
                if not status.get("cleared"):
                    wedge_since = status.get("since")
            except Exception:
                pass
        if wedge_since:
            try:
                import calendar

                age_h = (
                    time.time()
                    - calendar.timegm(time.strptime(wedge_since, "%Y-%m-%dT%H:%MZ"))
                ) / 3600.0
                age = f", ~{age_h:.0f}h old at bench time"
            except Exception:
                age = ""
            note += (
                f"; known chip-claim wedge since {wedge_since}{age} "
                f"(stale server-side session; recovery chain armed: "
                f"scripts/probe_tpu_loop.sh && scripts/tpu_evidence.py)"
            )

    # REAL MFU from the compiled programs (stderr; stdout stays the one-line
    # contract): XLA's cost_analysis of the exact generate/score/train_step
    # programs this bench executed — attention, collectives, everything —
    # instead of the hand-derived 2N/6N bound below. The programs are
    # already compiled (warmup), so lowering again is a cache hit.
    program_flops = (
        _program_cycle_flops(config, trainer, chunk) if not on_cpu else None
    )

    # Analytic MFU estimate (stderr; stdout stays the one-line contract).
    # Scaling-book accounting: forward ≈ 2·N FLOPs/token, backward ≈ 4·N
    # over the trainable fraction. Tokens per cycle: decode (prefill P +
    # N_new single-token steps), the scoring fwd (policy full + hydra ref
    # branch ≈ unfrozen fraction), and ppo_epochs train fwd+bwd. Attention
    # FLOPs (~3% at these shapes) excluded — a lower bound.
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(trainer.state.params)
    )
    seq = prompt_tokens + max_new
    n_unfrozen = config.model.num_layers_unfrozen
    unfrozen_frac = (
        1.0 if n_unfrozen < 0 else n_unfrozen / trainer.tcfg.num_layers
    )  # -1 sentinel = all layers trainable (mirrors _scan_layer_vector)
    tok = chunk * seq
    fwd = 2 * n_params
    cycle_flops = (
        tok * fwd  # decode (prefill + steps, cache makes each token one fwd)
        + tok * fwd * (1 + unfrozen_frac)  # scoring fwd + hydra ref branch
        + config.method.ppo_epochs * tok * (fwd + 2 * fwd * unfrozen_frac)
    )
    peak = float("nan")
    if not on_cpu:
        kind = getattr(devices[0], "device_kind", "").lower()
        gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
        # single source of truth shared with the runtime MFU metric
        from trlx_tpu.observability.metrics import TPU_PEAK_FLOPS as peaks

        for key, val in peaks.items():
            if key in kind or key == gen:
                peak = val  # bf16 peak per chip
                break
    mfu = cycle_flops * n_cycles / dt / (peak * max(n_dev, 1))
    mfu_real = (
        program_flops * n_cycles / dt / (peak * max(n_dev, 1))
        if program_flops is not None
        else float("nan")
    )
    print(
        json.dumps(
            {
                "mfu": round(mfu_real, 4) if np.isfinite(mfu_real) else None,
                "mfu_estimate": round(mfu, 4) if np.isfinite(mfu) else None,
                "samples_per_sec_per_chip": round(per_chip, 3),
                "cycle_tflops": round(cycle_flops / 1e12, 3),
                "program_cycle_tflops": (
                    round(program_flops / 1e12, 3)
                    if program_flops is not None
                    else None
                ),
                "note": (
                    "mfu = XLA cost_analysis flops of the executed "
                    "generate/score/train programs; mfu_estimate = analytic "
                    "2N/6N lower bound, attention excluded"
                ),
            }
        ),
        file=sys.stderr,
    )
    line = {
        "metric": "ppo_sentiments-shaped e2e throughput (gpt2-small, 64+40 tok)" + tag,
        "value": round(samples_per_sec, 3),
        "unit": "samples/sec",
        "vs_baseline": round(samples_per_sec / A100_BASELINE_SAMPLES_PER_SEC, 3),
        # observability-layer throughput fields (docs/OBSERVABILITY.md):
        # whole-sequence tokens per wall-second, and measured MFU from the
        # executed programs' XLA cost_analysis (null when no cost model)
        "tokens_per_sec": round(samples_per_sec * seq, 1),
        "mfu": round(mfu_real, 4) if np.isfinite(mfu_real) else None,
    }
    # rollout-pipeline overlap (docs/PERFORMANCE.md): fraction of the last
    # cycle's rollout wall-time in which host reward scoring was hidden
    # behind device generation (0.0 on the depth-0 serial path)
    overlap = trainer.make_experience_stats.get("throughput/rollout_overlap_frac")
    line["rollout_overlap_frac"] = (
        round(float(overlap), 4) if overlap is not None else None
    )
    # decode slot utilization (docs/PERFORMANCE.md): live slot-steps ÷ total
    # slot-steps of the last cycle's rollout decode. On the chunked paths it
    # is mask-derived (1 − batch-tail padding waste); with
    # train.continuous_batching (BENCH_CB=1) it comes from the slot-refill
    # engine's exact counters.
    slot_util = trainer.make_experience_stats.get("throughput/slot_utilization")
    line["slot_utilization"] = (
        round(float(slot_util), 4) if slot_util is not None else None
    )
    # paged-engine gauges (docs/PERFORMANCE.md): prefix-cache hit rate over
    # full prompt blocks and the block pool's high-water, from the last
    # cycle's rollout engine; null unless BENCH_ENGINE=1 selected the paged
    # backend (+ prefix cache)
    hit_rate = trainer.make_experience_stats.get("engine/prefix_hit_rate")
    line["prefix_hit_rate"] = (
        round(float(hit_rate), 4) if hit_rate is not None else None
    )
    blocks = trainer.make_experience_stats.get("engine/kv_blocks_in_use")
    line["kv_blocks_in_use"] = int(blocks) if blocks is not None else None
    # speculative-decoding gauge (docs/PERFORMANCE.md "Speculative
    # continuous batching"): fraction of draft proposals the target
    # accepted over the last cycle's collection; null unless BENCH_SPEC=1
    acc = trainer.make_experience_stats.get("engine/spec_acceptance_rate")
    line["spec_acceptance_rate"] = (
        round(float(acc), 4) if acc is not None else None
    )
    # async actor/learner gauges (docs/ASYNC_RL.md): fraction of the actor
    # fleet's wall-time spent waiting (staleness gate + queue back-pressure)
    # and the mean consumption staleness in learner updates, from the last
    # cycle's collection; null unless BENCH_ASYNC=1
    idle = trainer.make_experience_stats.get("async/actor_idle_frac")
    line["actor_idle_frac"] = round(float(idle), 4) if idle is not None else None
    stale = trainer.make_experience_stats.get("async/staleness_mean")
    line["mean_staleness"] = round(float(stale), 4) if stale is not None else None
    # collective fleet-transport gauges (docs/ASYNC_RL.md "Transports"):
    # ack-measured dissemination-tree latency and the learner's delta-publish
    # egress for the last cycle's collection; null unless BENCH_ASYNC=1 with
    # the collective transport
    diss = trainer.make_experience_stats.get("async/dissemination_latency_s")
    line["dissemination_latency_s"] = (
        round(float(diss), 6) if diss is not None else None
    )
    pub = trainer.make_experience_stats.get("async/publish_bytes")
    line["publish_bytes"] = int(pub) if pub is not None else None
    # resilience proof (docs/RESILIENCE.md): "ok" when the warmup cycle's
    # injected reward outage was retried away AND the injected NaN step left
    # the weights finite (update guard); null when BENCH_FAULTS=0
    line["fault_recovery"] = fault_recovery
    # elastic proof (docs/RESILIENCE.md "Elastic restore"): "ok" when the
    # untimed shrink-restore probe round-tripped the train state through a
    # halved mesh (or, single-device, through the forced reshard path)
    # byte-identically; null when BENCH_FAULTS=0
    line["elastic_recovery"] = elastic_recovery
    # flight-recorder proof (docs/OBSERVABILITY.md "Flight recorder"): "ok"
    # when the untimed dump+reload probe found span AND metric records in
    # the ring the warmup populated; null when BENCH_FAULTS=0
    line["flight_recorder"] = flight_recorder
    # serving proof (docs/SERVING.md): "ok" when the untimed probe streamed
    # a real HTTP request end-to-end off the published params AND the
    # admission flood drill shed load with 429s; null when BENCH_SERVE=0
    line["serving"] = serving
    # RL health verdict (docs/OBSERVABILITY.md "Training dynamics"): "ok"
    # or the first tripped detector at the end of the timed cycles — a
    # degenerate-run artifact is labeled as such, not read as a perf number
    try:
        line["health"] = str(trainer.obs.health.verdict)
    except Exception:
        line["health"] = None
    # cross-rank step skew (docs/OBSERVABILITY.md "Distributed telemetry"):
    # max−min per-rank step time at the last cluster beat — 0.0 on a
    # single process, the straggler signal on a pod
    skew = trainer.obs.metrics.snapshot(reset_histograms=False).get(
        "cluster/step_skew_s"
    )
    line["step_skew_s"] = round(float(skew), 4) if skew is not None else None
    if note:
        line["note"] = note
    # the headline contract is emitted BEFORE the optional xl stage: an
    # xl-stage overrun (or external kill) can only cost the extra point,
    # never the artifact the driver parses
    print(json.dumps(line), flush=True)

    # drop the 124M trainer (params, optimizer state, hydra ref, rollout
    # store) before the 1.5B build — on a single chip the two don't need to
    # coexist in HBM. The cycle closure captures the trainer, so it must be
    # dropped too. Async actor threads must stop first (they hold params).
    trainer._shutdown_collectors()
    trainer = None
    one_cycle = None
    _maybe_xl_stage(on_cpu, peak, reward_fn)


if __name__ == "__main__":
    sys.exit(main())
